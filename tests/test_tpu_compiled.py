"""On-chip (compiled Mosaic) kernel regression tests — ``pytest -m tpu``.

Every other test in this suite runs the Pallas kernels in interpret mode
on CPU (tests/conftest.py forces the CPU backend).  This file is the
complement: it compiles the flash forward/backward, flash-quantized, and
paged-attention kernels on the real TPU chip and asserts parity against
the XLA reference paths — turning the round-2 prose claims
("compiled-vs-interpret parity ~7e-5", "int8 flash vs dequantized sdpa
rel ~4e-3", ROADMAP.md) into runnable regressions.

Run with ``python -m pytest tests/ -m tpu`` ON A TPU HOST: the conftest
leaves the real backend in place only when the marker expression is
exactly ``tpu`` (any other invocation forces CPU and these tests
auto-skip).  The reference's analogue is its CUDA-gated tier-3 harness
(``/root/reference/jax_test.py:428-429``); here the on-chip tier is a
first-class pytest marker instead of a manual script.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.tpu

requires_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="needs the real TPU chip (run: pytest -m tpu)",
)


def _rel(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return np.abs(a - b).max() / max(np.abs(b).max(), 1e-9)


@requires_tpu
@pytest.mark.parametrize("blk", [8, 16, 32, 64, 128])
@pytest.mark.parametrize("quantized", [False, True])
def test_paged_kernel_block_sizes_compiled(blk, quantized):
    """The serving eligibility gate is block_size % 8 == 0; this is the
    hardware evidence behind it (ADVICE r2): every narrow-lane block size
    compiles under Mosaic and matches interpret mode, bf16 and int8."""
    from jax_llama_tpu.ops.paged_attention import paged_pool_attention

    B, KVH, G, d = 4, 4, 2, 128
    NB, MB = 16, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, KVH, G, d), jnp.bfloat16)
    table = jnp.asarray(
        np.arange(B * MB, dtype=np.int32).reshape(B, MB) % NB
    )
    pos = jnp.asarray(np.tile(np.arange(blk, dtype=np.int32), (NB, 1)))
    qpos = jnp.asarray(np.full((B,), blk - 1, np.int32))
    if quantized:
        kp = jnp.asarray(rng.randint(-127, 128, (KVH, NB, blk, d)), jnp.int8)
        vp = jnp.asarray(rng.randint(-127, 128, (KVH, NB, blk, d)), jnp.int8)
        ks = jnp.asarray(rng.rand(KVH, NB, blk) * 0.02, jnp.float32)
        vs = jnp.asarray(rng.rand(KVH, NB, blk) * 0.02, jnp.float32)
        scales = dict(k_scale=ks, v_scale=vs)
    else:
        kp = jnp.asarray(rng.randn(KVH, NB, blk, d), jnp.bfloat16)
        vp = jnp.asarray(rng.randn(KVH, NB, blk, d), jnp.bfloat16)
        scales = {}
    out_c, lse_c = paged_pool_attention(
        q, kp, vp, pos, table, qpos, interpret=False, **scales
    )
    out_i, lse_i = paged_pool_attention(
        q, kp, vp, pos, table, qpos, interpret=True, **scales
    )
    assert np.isfinite(np.asarray(out_c, np.float32)).all()
    assert _rel(out_c, out_i) < 1e-5
    assert np.abs(np.asarray(lse_c) - np.asarray(lse_i)).max() < 1e-4


@requires_tpu
@pytest.mark.parametrize("S", [1024, 4096])
def test_flash_forward_compiled_parity(S):
    """Compiled flash forward vs (a) interpret mode and (b) the dense XLA
    sdpa path, at prefill shapes."""
    from jax_llama_tpu.ops.attention import attention_bias, sdpa
    from jax_llama_tpu.ops.flash_attention import flash_attention

    B, H, KVH, d = 1, 8, 4, 128
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, d) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    pos = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))

    out_c = flash_attention(q, k, v, pos, pos, interpret=False)
    out_i = flash_attention(q, k, v, pos, pos, interpret=True)
    # Same blockwise arithmetic, compiled vs emulated: tight.
    assert _rel(out_c, out_i) < 5e-4
    bias = attention_bias(pos, pos, pos >= 0)
    ref = sdpa(q, k, v, bias)
    # Different reduction orders in bf16: loose.
    assert _rel(out_c, ref) < 2e-2


@requires_tpu
def test_flash_backward_compiled_parity():
    """Compiled flash VJP (dq/dk/dv) vs the dense sdpa VJP on chip.

    S=2048 so the backward kernels compile at the FULL default tile
    (block_q=1024 — live since GQA packing doubles the row axis — AND
    block_k=2048); smaller S silently clamps and would leave the default
    shape Mosaic-untested."""
    from jax_llama_tpu.ops.attention import attention_bias, sdpa
    from jax_llama_tpu.ops.flash_attention import flash_attention

    B, S, H, KVH, d = 1, 2048, 8, 4, 128
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(B, S, H, d) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    pos = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))
    g = jnp.asarray(rng.randn(B, S, H, d) * 0.3, jnp.bfloat16)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, pos, pos, interpret=False)
            .astype(jnp.float32) * g.astype(jnp.float32)
        )

    def loss_ref(q, k, v):
        bias = attention_bias(pos, pos, pos >= 0)
        return jnp.sum(
            sdpa(q, k, v, bias).astype(jnp.float32)
            * g.astype(jnp.float32)
        )

    gq, gk, gv = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    rq, rk, rv = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    assert _rel(gq, rq) < 3e-2
    assert _rel(gk, rk) < 3e-2
    assert _rel(gv, rv) < 3e-2


@requires_tpu
def test_flash_quantized_compiled_parity():
    """Compiled int8-KV flash kernel vs sdpa over the dequantized cache
    (the r2 claim: rel ~4e-3 — int8-rounding noise level in bf16)."""
    from jax_llama_tpu.models.llama import quantize_kv
    from jax_llama_tpu.ops.attention import attention_bias, sdpa
    from jax_llama_tpu.ops.flash_attention import flash_attention_quantized

    B, S, H, KVH, d = 2, 512, 8, 4, 128
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, S, H, d) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, KVH, d) * 0.3, jnp.bfloat16)
    pos = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1))

    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    out_c = flash_attention_quantized(
        q, kq, vq, ks, vs, pos, pos, interpret=False
    )
    kd = (kq.astype(jnp.float32) * ks[..., None]).astype(jnp.bfloat16)
    vd = (vq.astype(jnp.float32) * vs[..., None]).astype(jnp.bfloat16)
    bias = attention_bias(pos, pos, pos >= 0)
    ref = sdpa(q, kd, vd, bias)
    assert _rel(out_c, ref) < 2e-2


@requires_tpu
def test_model_decode_on_chip_flash_vs_xla():
    """Model-level canary: short greedy decode on the chip must agree
    between attn_impl='auto' (flash prefill + xla decode) and pure 'xla',
    and produce finite logits."""
    import jax_llama_tpu as jlt
    from jax_llama_tpu.engine import GenerationConfig, generate

    rng = np.random.RandomState(4)
    kw = dict(
        vocab_size=512, dim=256, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=256, dtype="bfloat16",
        param_dtype="bfloat16",
    )
    cfg_auto = jlt.get_config("tiny", **kw)
    params = jlt.init_params(jax.random.PRNGKey(0), cfg_auto)
    tokens = jnp.asarray(rng.randint(1, 512, (2, 32)), jnp.int32)
    mask = jnp.ones((2, 32), bool)
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    out_auto = np.asarray(generate(
        params, tokens, mask, jax.random.PRNGKey(0), config=cfg_auto,
        gen_config=gc,
    ))
    cfg_xla = cfg_auto.replace(attn_impl="xla")
    out_xla = np.asarray(generate(
        params, tokens, mask, jax.random.PRNGKey(0), config=cfg_xla,
        gen_config=gc,
    ))
    # bf16 near-ties can legitimately flip a late token; require the
    # first half of the generations to agree exactly.
    assert (out_auto[:, : 32 + 4] == out_xla[:, : 32 + 4]).all()


@requires_tpu
def test_paged_decode_step_no_full_pool_copies_compiled():
    """Two r4 wins, pinned against regression in the COMPILED decode
    step's optimized HLO:

    * the batched pool scatter used to make XLA:TPU relayout the whole
      KV pool to a KVH-minor layout and back every step (four full-pool
      copies, ~3.2 ms/step at bench scale) — replaced by
      ``paged_pool_write``'s in-place dynamic_update_slice chain;
    * the layer scan used to materialize every layer's pool plane as a
      dynamic-slice copy feeding the kernel's custom-call operand
      (~3x the kernel's own time at 16k) — replaced by the
      layer-indexed kernel reading the full pool in place.

    Either regression reappears as a `copy` / dynamic-slice fusion of a
    pool-sized [L, KVH, NB, BLK, d] (or one-layer [KVH, NB, BLK, d])
    array in the HLO text, so assert there is none.
    """
    import re

    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.serving import ContinuousBatcher

    # bf16 params: the serving dtype.  (An fp32 pool additionally gets a
    # pair of async memory-space staging copies from XLA:TPU that are
    # unrelated to either regression guarded here.)
    cfg = get_config(
        "tiny", dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
        vocab_size=512, max_seq_len=256, param_dtype="bfloat16",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=4, max_len=256,
                           block_size=32)
    rng = np.random.RandomState(5)
    for _ in range(4):
        cb.submit(list(rng.randint(1, cfg.vocab_size, 100)),
                  max_new_tokens=4)
    cb.step()  # admission; decode-step program now has concrete args

    from jax_llama_tpu import serving as srv

    L, KVH = cfg.n_layers, cfg.kv_heads
    NB, BLK = cb.pool.pos.shape
    d = cfg.head_dim
    lowered = srv._paged_decode_step.lower(
        cb.params, cb.pool, jnp.array(cb.table), jnp.array(cb.n_alloc),
        jnp.array(cb.fill), cb.tau, jnp.array(cb.pos),
        jnp.array(cb.active), cb.keys, jnp.array(cb.temp_arr),
        jnp.array(cb.top_p_arr), jnp.array(cb.top_k_arr),
        config=cb.config, all_greedy=True, mesh=None, allow_kernel=True,
        with_logprobs=False,
    )
    txt = lowered.compile().as_text()
    pool_shape = rf"{L},{KVH},{NB},{BLK},{d}"
    plane_shape = rf"{KVH},{NB},{BLK},{d}"
    offenders = [
        line.strip()[:140]
        for line in txt.splitlines()
        if re.search(rf"(copy|dynamic-slice)[^=]*=[^=]*\[({pool_shape}|{plane_shape})\]", line)
        or (" copy(" in line and f"[{pool_shape}]" in line)
    ]
    assert not offenders, offenders


@requires_tpu
def test_paged_decode_chunk_no_full_pool_copies_compiled():
    """The fused K-iteration chunk program (the serving hot path since
    chunked decode) must uphold the same no-full-pool-copy invariant as
    the single-step program above: the pool rides the decode scan as a
    donated carry, and the classic way THAT breaks is XLA materializing
    a pool-sized copy at the scan boundary — which would double KV HBM
    and regress ~ms/step silently.  Same HLO-text assertion, against the
    n_iter=4 chunk executable with the device-resident state args the
    batcher actually dispatches."""
    import re

    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.serving import ContinuousBatcher

    cfg = get_config(
        "tiny", dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
        vocab_size=512, max_seq_len=256, param_dtype="bfloat16",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=4, max_len=256,
                           block_size=32, decode_chunk=4)
    rng = np.random.RandomState(5)
    for _ in range(4):
        cb.submit(list(rng.randint(1, cfg.vocab_size, 100)),
                  max_new_tokens=8)
    cb.step()  # admission; chunk program now has concrete args

    from jax_llama_tpu import serving as srv

    L, KVH = cfg.n_layers, cfg.kv_heads
    NB, BLK = cb.pool.pos.shape
    d = cfg.head_dim
    lowered = srv._paged_decode_chunk.lower(
        cb.params, cb.pool, cb.d_table, cb.d_n_alloc, cb.d_fill,
        cb.tau, cb.d_tau_lp, cb.d_pos, cb.d_active, cb.d_remaining,
        cb.d_stops, cb.keys, cb.d_temps, cb.d_top_ps, cb.d_top_ks,
        config=cb.config, n_iter=4, all_greedy=True, mesh=None,
        allow_kernel=True, with_logprobs=False,
    )
    txt = lowered.compile().as_text()
    pool_shape = rf"{L},{KVH},{NB},{BLK},{d}"
    plane_shape = rf"{KVH},{NB},{BLK},{d}"
    offenders = [
        line.strip()[:140]
        for line in txt.splitlines()
        if re.search(rf"(copy|dynamic-slice)[^=]*=[^=]*\[({pool_shape}|{plane_shape})\]", line)
        or (" copy(" in line and f"[{pool_shape}]" in line)
    ]
    assert not offenders, offenders


@requires_tpu
def test_spec_rounds_chunk_no_full_pool_copies_compiled():
    """The fused R-round speculative program (``_spec_rounds_chunk``)
    must uphold the same no-full-pool-copy invariant as the decode
    chunk above — with TWO pools riding the scan carry (target +
    draft), an XLA-materialized pool-sized copy at the scan boundary
    would double BOTH KV footprints and silently regress every round.
    Same HLO-text assertion, against the n_rounds=4 executable with the
    device-resident state args the batcher actually dispatches
    (self-draft, so one shape pattern covers both pools)."""
    import re

    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.serving import ContinuousBatcher

    cfg = get_config(
        "tiny", dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
        vocab_size=512, max_seq_len=256, param_dtype="bfloat16",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=4, max_len=256,
                           block_size=32, spec_rounds=4,
                           draft_params=params, draft_config=cfg,
                           n_draft=3)
    rng = np.random.RandomState(5)
    for _ in range(4):
        cb.submit(list(rng.randint(1, cfg.vocab_size, 100)),
                  max_new_tokens=16)
    cb.step()  # admission; the fused spec program now has concrete args

    from jax_llama_tpu import serving as srv

    L, KVH = cfg.n_layers, cfg.kv_heads
    NB, BLK = cb.pool.pos.shape
    d = cfg.head_dim
    lowered = srv._spec_rounds_chunk.lower(
        cb.params, cb.draft_params, cb.pool, cb.draft_pool, cb.d_table,
        cb.d_n_alloc, cb.d_fill, cb.tau, cb.d_tau_lp, cb.d_pos,
        cb.d_active, cb.d_remaining, cb.d_stops, cb.keys, cb.d_temps,
        cb.d_top_ps, cb.d_top_ks,
        t_config=cb.config, d_config=cb.draft_config,
        n_draft=cb.n_draft, n_rounds=4, all_greedy=True,
        use_kernel=True, mesh=None, with_logprobs=False,
    )
    txt = lowered.compile().as_text()
    pool_shape = rf"{L},{KVH},{NB},{BLK},{d}"
    plane_shape = rf"{KVH},{NB},{BLK},{d}"
    offenders = [
        line.strip()[:140]
        for line in txt.splitlines()
        if re.search(rf"(copy|dynamic-slice)[^=]*=[^=]*\[({pool_shape}|{plane_shape})\]", line)
        or (" copy(" in line and f"[{pool_shape}]" in line)
    ]
    assert not offenders, offenders


@requires_tpu
def test_fused_chunk_no_full_pool_copies_compiled():
    """The fused prefill-decode program (``_fused_chunk``, the serving
    hot path while an admission is mid-prefill) must uphold the same
    lowering invariants as the plain chunk program: the KV pool and the
    per-slot batcher state ride as DONATED carries (the entry
    computation carries input_output_alias entries for them) and no
    pool-sized copy/dynamic-slice appears — the prefill half gathers
    ONE row's view, never the pool, and the decode scan's carry must
    not materialize a pool copy at the scan boundary.  Same HLO-text
    assertion as its siblings, against the live mid-prefill args the
    batcher actually dispatches."""
    import re

    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.serving import ContinuousBatcher

    cfg = get_config(
        "tiny", dim=256, n_layers=4, n_heads=4, n_kv_heads=2,
        vocab_size=512, max_seq_len=256, param_dtype="bfloat16",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=4, max_len=256,
                           block_size=32, decode_chunk=4,
                           prefill_budget=64)
    rng = np.random.RandomState(5)
    cb.submit(list(rng.randint(1, cfg.vocab_size, 100)),
              max_new_tokens=16)
    cb.step()  # cold classic admission
    cb.step()
    cb.submit(list(rng.randint(1, cfg.vocab_size, 100)),
              max_new_tokens=16)
    cb.step()  # fused prefill starts (128-token suffix > one 64 chunk)
    assert cb._pf is not None  # the fused program has concrete args

    from jax_llama_tpu import serving as srv

    pf = cb._pf
    L, KVH = cfg.n_layers, cfg.kv_heads
    NB, BLK = cb.pool.pos.shape
    d = cfg.head_dim
    lowered = srv._fused_chunk.lower(
        cb.params, cb.pool, cb.d_table, cb.d_n_alloc, cb.d_fill,
        cb.tau, cb.d_tau_lp, cb.d_pos, cb.d_active, cb.d_remaining,
        cb.d_stops, cb.keys, cb.d_temps, cb.d_top_ps, cb.d_top_ks,
        pf.d_row, pf.d_toks, pf.d_len, pf.d_base, pf.d_off, pf.d_key,
        config=cb.config, n_iter=4, pf_chunk=pf.chunk,
        all_greedy=True, mesh=None, allow_kernel=True,
        with_logprobs=False,
    )
    txt = lowered.compile().as_text()
    # Donation pin: the pool and the decode-state carries alias inputs
    # to outputs (a dropped donate_argnames entry would silently double
    # KV HBM and re-upload state every dispatch).
    assert "input_output_alias" in txt
    pool_shape = rf"{L},{KVH},{NB},{BLK},{d}"
    plane_shape = rf"{KVH},{NB},{BLK},{d}"
    offenders = [
        line.strip()[:140]
        for line in txt.splitlines()
        if re.search(rf"(copy|dynamic-slice)[^=]*=[^=]*\[({pool_shape}|{plane_shape})\]", line)
        or (" copy(" in line and f"[{pool_shape}]" in line)
    ]
    assert not offenders, offenders


@requires_tpu
def test_device_op_times_compiled():
    """utils.profiling.device_op_times — the measurement primitive behind
    every bench/ROADMAP perf number — attributes device time to a known
    dominant op, in both aggregation modes, on a real trace."""
    from jax_llama_tpu.utils.profiling import device_op_times

    a = jnp.ones((1024, 1024), jnp.bfloat16)

    @jax.jit
    def f(x):
        return (x @ x).sum()

    float(f(a))  # compile outside the trace
    by_op = device_op_times(lambda: float(f(a)), by="op")
    assert by_op and all(v >= 0 for v in by_op.values())
    # The matmul fusion dominates a trace whose only work is a matmul.
    top = max(by_op, key=by_op.get)
    assert "fusion" in top or "convolution" in top or "dot" in top, top
    by_src = device_op_times(lambda: float(f(a)), by="source")
    assert sum(by_src.values()) > 0


@requires_tpu
def test_suffix_admission_parity_on_chip():
    """Prefix-cache hit admission vs cold full prefill, ON CHIP in the
    serving dtype (bf16): token identity.

    The CPU fp32 suite pins this (tests/test_prefix_cache.py), but the
    suffix path computes its activations through a differently-shaped
    dispatch than a cold prefill (gathered-view ``_paged_suffix_insert``
    vs batched ``_paged_insert``), so bf16 on-chip identity was a
    measured claim, not a theorem — this is the regression for it
    (ADVICE r5 follow-up to the softened ``--no-prefix-cache`` doc)."""
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.serving import ContinuousBatcher

    cfg = get_config(
        "tiny", vocab_size=512, dim=256, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=256,
        dtype="bfloat16", param_dtype="bfloat16",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(6)
    system = rng.randint(1, 512, size=40).tolist()  # 2 full 16-blocks
    submits = [
        (system + rng.randint(1, 512, size=5).tolist(),
         dict(max_new_tokens=8)),
        (system + rng.randint(1, 512, size=7).tolist(),
         dict(max_new_tokens=8, temperature=0.8, seed=7)),
    ]

    cold = ContinuousBatcher(params, cfg, n_slots=1, max_len=128,
                             block_size=16, prefix_cache=False)
    cold_out = []
    for p, kw in submits:
        rid = cold.submit(list(p), **kw)
        cold_out.append(cold.run_to_completion()[rid])

    warm = ContinuousBatcher(params, cfg, n_slots=1, max_len=128,
                             block_size=16, prefix_cache=True)
    warm_out = []
    for p, kw in submits:
        rid = warm.submit(list(p), **kw)
        warm_out.append(warm.run_to_completion()[rid])

    st = warm.stats()
    assert st["prefix_requests_hit_total"] == 1  # the hit actually ran
    assert st["prefix_blocks_reused_total"] == 2
    assert warm_out == cold_out  # on-chip suffix insert is emit-identical
