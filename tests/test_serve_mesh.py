"""Scale-out serving: mesh-sharded chunk programs + sharded KV pool.

The acceptance pins (ISSUE 10): on the forced 8-device CPU host mesh,
the sharded ``_paged_decode_chunk`` / ``_fused_chunk`` programs are
TOKEN-IDENTICAL to single-chip (logprobs allclose — cross-shard
reduction order wobbles fp32 at ~1e-6), the pool/state placement is
the canonical one (KV heads over ``tensor``, state rows over ``data``)
and STABLE across dispatches (the donated-alias precondition the
lowering auditor's mesh pass proves per-program), and the
prefill/decode disaggregation handoff moves prefix KV between batchers
token-identically.  The first sharded dispatch in each test runs under
``conftest.mesh_guarded`` so this image's known PartitionId/SPMD skew
skips cleanly instead of failing."""

import jax
import numpy as np
import pytest

from conftest import mesh_guarded
from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.parallel import serve_mesh as smesh
from jax_llama_tpu.parallel.mesh import make_mesh
from jax_llama_tpu.parallel.partition import shard_params
from jax_llama_tpu.serving import ContinuousBatcher

pytestmark = pytest.mark.mesh_serving

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


@pytest.fixture(scope="module")
def mesh22(model, cpu_mesh_devices):
    """data=2 x tensor=2 serving mesh + params sharded onto it."""
    params, config = model
    mesh = make_mesh(data=2, tensor=2, devices=cpu_mesh_devices[:4])
    return mesh, shard_params(params, mesh, config)


def _serve(params, config, mesh, *, prefill_budget=0, logprobs=False,
           fused_admission=False, **cb_kw):
    """The shared request mix (greedy stopping mid-chunk + seeded
    sampled) + optionally a long prompt admitted MID-DECODE so the
    fused prefill lane engages.  Geometry kept to n_slots=2 /
    decode_chunk=2 deliberately: every extra row or K specialization
    compiles another mesh executable, and tier-1's budget cannot
    absorb it (the broader shapes ride the slow tier / make
    mesh-serve).  Returns ([tokens...], [logprobs...], batcher) per
    request in submit order."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, size=n).tolist() for n in (5, 9)]
    policies = [
        dict(max_new_tokens=4),
        dict(max_new_tokens=6, temperature=0.9, seed=11),
    ]
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, decode_chunk=2,
        mesh=mesh, prefill_budget=prefill_budget, logprobs=logprobs,
        **cb_kw,
    )
    rids = [cb.submit(p, **pol) for p, pol in zip(prompts, policies)]
    toks, lps = {}, {}

    def drain_some(n):
        for _ in range(n):
            for ev in cb.step():
                toks.setdefault(ev[0], []).append(ev[1])
                if logprobs:
                    lps.setdefault(ev[0], []).append(ev[3])

    mesh_guarded(drain_some, 2)
    if fused_admission:
        # Long prompt lands while rows decode -> the fused prefill lane
        # (or, at prefill_budget=0, a classic mid-decode insert).
        long = rng.randint(1, 128, size=40).tolist()
        rids.append(cb.submit(long, max_new_tokens=3, seed=99))
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 500
        drain_some(1)
    return [toks[r] for r in rids], [lps.get(r) for r in rids], cb


def _assert_parity(base, mesh_out):
    b_toks, b_lps, _ = base
    m_toks, m_lps, _ = mesh_out
    assert m_toks == b_toks
    for bl, ml in zip(b_lps, m_lps):
        if bl is not None:
            np.testing.assert_allclose(ml, bl, rtol=1e-4, atol=1e-5)


def test_sharded_chunk_programs_parity(model, mesh22):
    """ACCEPTANCE PIN: sharded ``_fused_chunk`` AND
    ``_paged_decode_chunk`` ≡ single-chip — tokens exact (greedy AND
    seeded sampling, long admission riding the prefill lane
    mid-decode), logprobs allclose.  One scenario covers both
    programs: dispatches WITH the in-flight admission run
    ``_fused_chunk``, dispatches without run ``_paged_decode_chunk``
    (asserted via the dispatch counters below)."""
    params, config = model
    mesh, sp = mesh22
    base = _serve(params, config, None, prefill_budget=16,
                  logprobs=True, fused_admission=True)
    out = _serve(sp, config, mesh, prefill_budget=16, logprobs=True,
                 fused_admission=True)
    _assert_parity(base, out)
    cb = out[2]
    assert cb._mesh_placed
    # Both programs actually dispatched on the mesh run.
    assert cb.fused_admissions_total >= 1
    assert cb.prefill_chunks_total >= 1
    assert cb.decode_dispatches_total > cb.prefill_chunks_total


@pytest.mark.slow
def test_sharded_decode_chunk_only_parity(model, mesh22):
    """The prefill-free configuration (classic admission,
    ``_paged_decode_chunk`` exclusively) — the tier-1 pin above covers
    the program; this cell pins the prefill_budget=0 config too."""
    params, config = model
    mesh, sp = mesh22
    base = _serve(params, config, None, logprobs=True)
    out = _serve(sp, config, mesh, logprobs=True)
    _assert_parity(base, out)
    assert out[2]._mesh_placed


def test_pool_and_state_placement_stable(model, mesh22):
    """The pool shards KV heads over ``tensor``, per-slot twins shard
    rows over ``data``, and BOTH keep their sharding across dispatches
    — the aliasing precondition (drift = a reshard + silent donation
    copy every chunk)."""
    params, config = model
    mesh, sp = mesh22
    # Same geometry as the parity pin above -> its executables are jit
    # cache hits; this test pays dispatches only.
    cb = ContinuousBatcher(
        sp, config, n_slots=2, max_len=128, decode_chunk=2, mesh=mesh,
    )
    assert cb._mesh_placed
    rid = cb.submit([5, 17, 99, 3, 42], max_new_tokens=6)

    def spec_of(a):
        return a.sharding

    from jax.sharding import NamedSharding

    want_pool = NamedSharding(
        mesh, smesh.pool_pspec("k", cb.pool.k.ndim)
    )
    assert cb.pool.k.sharding.is_equivalent_to(
        want_pool, cb.pool.k.ndim
    )
    mesh_guarded(cb.step)
    first = {
        "k": spec_of(cb.pool.k), "pos": spec_of(cb.pool.pos),
        "fill": spec_of(cb.d_fill), "table": spec_of(cb.d_table),
        "keys": spec_of(cb.keys),
    }
    while cb.pending():
        cb.step()
    after = {
        "k": spec_of(cb.pool.k), "pos": spec_of(cb.pool.pos),
        "fill": spec_of(cb.d_fill), "table": spec_of(cb.d_table),
        "keys": spec_of(cb.keys),
    }
    for name in first:
        a, b = first[name], after[name]
        arr = {"k": cb.pool.k, "pos": cb.pool.pos, "fill": cb.d_fill,
               "table": cb.d_table, "keys": cb.keys}[name]
        assert a.is_equivalent_to(b, arr.ndim), name
    # KV-head axis genuinely sharded over tensor: each shard holds
    # KVH/tp heads' blocks.
    shard_shape = cb.pool.k.sharding.shard_shape(cb.pool.k.shape)
    assert shard_shape[1] == config.kv_heads // 2
    _ = rid


def test_spec_parse_build_validate(model, cpu_mesh_devices):
    params, config = model
    assert smesh.parse_serve_mesh("2,4") == smesh.ServeMeshSpec(2, 4)
    assert smesh.parse_serve_mesh("4") == smesh.ServeMeshSpec(1, 4)
    with pytest.raises(ValueError):
        smesh.parse_serve_mesh("2,4,8")
    with pytest.raises(ValueError):
        smesh.parse_serve_mesh("zero,none")
    spec = smesh.parse_serve_mesh("2,2")
    mesh = smesh.build_serve_mesh(spec, devices=cpu_mesh_devices[:4])
    smesh.validate_serve_mesh(config, mesh, n_slots=4)
    with pytest.raises(ValueError):  # rows must divide slots
        smesh.validate_serve_mesh(config, mesh, n_slots=3)
    with pytest.raises(ValueError):  # tensor must divide kv_heads
        smesh.validate_serve_mesh(
            config, make_mesh(tensor=8, devices=cpu_mesh_devices),
            n_slots=8,
        )
    with pytest.raises(ValueError):  # no seq/stage axes
        smesh.validate_serve_mesh(
            config,
            make_mesh(seq=2, tensor=2, data=2,
                      devices=cpu_mesh_devices),
            n_slots=8,
        )
    assert smesh.mesh_shape(mesh) == {
        "data": 2, "tensor": 2, "devices": 4,
    }
    assert smesh.mesh_shape(None) == {
        "data": 1, "tensor": 1, "devices": 1,
    }


def test_placement_envelope(model, cpu_mesh_devices):
    """Meshes outside the envelope keep legacy (unplaced) behavior
    rather than erroring: seq/stage axes or a non-dividing tensor."""
    params, config = model
    seq_mesh = make_mesh(seq=2, tensor=2, data=2,
                         devices=cpu_mesh_devices)
    assert not smesh.placement_ok(config, seq_mesh, 8)
    tp8 = make_mesh(tensor=8, devices=cpu_mesh_devices)
    assert not smesh.placement_ok(config, tp8, 8)  # kv_heads=2 % 8
    ok = make_mesh(data=2, tensor=2, devices=cpu_mesh_devices[:4])
    assert smesh.placement_ok(config, ok, 4)
    assert not smesh.placement_ok(config, ok, 3)  # rows don't divide
    assert not smesh.placement_ok(config, None, 4)


@pytest.mark.slow
def test_kv_handoff_token_identity(model):
    """Disaggregation skeleton: prefill on A, export/import the chain,
    serve on B as a prefix hit — token-identical to a cold serve."""
    params, config = model
    prompt = list(np.random.RandomState(3).randint(1, 128, 50))

    def serve(cb):
        r = cb.submit(prompt, max_new_tokens=6, seed=5)
        return cb.run_to_completion()[r]

    def mk():
        return ContinuousBatcher(
            params, config, n_slots=2, max_len=128, block_size=16,
            decode_chunk=4,
        )

    a = mk()
    out_a = serve(a)
    keys, slabs = a.export_prefix(prompt)
    assert len(slabs) == (len(prompt) - 1) // 16
    assert a.kv_export_blocks_total == len(slabs)

    cold = serve(mk())
    b = mk()
    n = b.import_prefix(keys, slabs)
    assert n == len(slabs)
    assert b.kv_import_blocks_total == n
    out_b = serve(b)
    assert out_a == cold
    assert out_b == cold
    assert b.prefix_requests_hit == 1
    assert b.prefix_blocks_reused == n
    # Re-import is a no-op (already resident).
    assert b.import_prefix(keys, slabs) == 0
    # Off-cache batchers export/import nothing.
    off = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, block_size=16,
        prefix_cache=False,
    )
    assert off.export_prefix(prompt) == ([], [])
    assert off.import_prefix(keys, slabs) == 0


@pytest.mark.slow
def test_sharded_host_tier_restore_on_mesh(model, mesh22):
    """The host-DRAM tier under sharded placement: demote a served
    chain, re-admit the session so the swap-in restores through
    mesh-placed staging buffers (``staging_shardings``), and the
    restored serve stays token-identical — with the pool keeping its
    canonical sharding through the adopt scatter."""
    params, config = model
    mesh, sp = mesh22

    def mk(p, m):
        return ContinuousBatcher(
            p, config, n_slots=4, max_len=128, block_size=16,
            decode_chunk=4, mesh=m, host_kv_blocks=16,
        )

    prompt = list(np.random.RandomState(5).randint(1, 128, 50))

    def serve(cb):
        r = cb.submit(prompt, max_new_tokens=5, seed=7)
        out = {}
        guard = 0
        while cb.pending():
            guard += 1
            assert guard < 500
            for ev in cb.step():
                out.setdefault(ev[0], []).append(ev[1])
        return out[r]

    base_cb = mk(params, None)
    want = serve(base_cb)

    cb = mk(sp, mesh)
    got = mesh_guarded(serve, cb)
    assert got == want
    n = cb.demote_idle(8)
    assert n > 0
    assert cb._store.host_blocks() == n
    got2 = serve(cb)  # re-admission swaps the chain back in
    assert got2 == want
    assert cb.swap_in_blocks_total > 0
    from jax.sharding import NamedSharding

    assert cb.pool.k.sharding.is_equivalent_to(
        NamedSharding(mesh, smesh.pool_pspec("k", cb.pool.k.ndim)),
        cb.pool.k.ndim,
    )


@pytest.mark.slow
def test_tensor_only_mesh_parity(model, cpu_mesh_devices):
    """A 1 x tensor=2 mesh (pure TP replica slice, the router's usual
    per-replica geometry) is also token-identical."""
    params, config = model
    mesh = make_mesh(tensor=2, devices=cpu_mesh_devices[:2])
    sp = shard_params(params, mesh, config)
    base = _serve(params, config, None, prefill_budget=16,
                  logprobs=True, fused_admission=True)
    out = _serve(sp, config, mesh, prefill_budget=16, logprobs=True,
                 fused_admission=True)
    _assert_parity(base, out)


@pytest.mark.slow
def test_sharded_spec_chunk_parity(model, mesh22):
    """Speculative chunked serving (R>1, both pools sharded) on the
    mesh ≡ single-chip: tokens and acceptance-driven emission exact."""
    params, config = model
    mesh, sp = mesh22

    def run(p, m):
        cb = ContinuousBatcher(
            p, config, n_slots=4, max_len=128, decode_chunk=1,
            spec_rounds=2, draft_params=p, draft_config=config,
            n_draft=2, mesh=m,
        )
        rids = [
            cb.submit([5, 17, 99, 3, 42], max_new_tokens=6),
            cb.submit([7, 8, 9], max_new_tokens=5, temperature=0.8,
                      seed=13),
        ]
        out = {}
        guard = 0
        while cb.pending():
            guard += 1
            assert guard < 500
            for ev in cb.step():
                out.setdefault(ev[0], []).append(ev[1])
        return [out[r] for r in rids], cb

    base, _ = run(params, None)
    got, cb = mesh_guarded(run, sp, mesh)
    assert got == base
    assert cb._mesh_placed
