"""Overload control (overload.py + the server wiring):

  * the brownout ladder escalates one rung at a time under sustained
    SLO pressure (dwell), recovers one rung at a time after calm
    (cooldown), and holds inside the hysteresis band;
  * admission is deadline-aware (a request whose timeout_s provably
    cannot be met is refused 503 with a load-derived Retry-After) and
    class-aware (strict interactive-first ordering; batch suspended at
    brownout-2, queued batch shed at 'shed' — cleanly, never a hang);
  * the flood drill: an open-loop Poisson mixed-class flood leaves
    zero hung clients, every 503 carries Retry-After, and the ladder
    steps back down to normal after the flood;
  * controller state (rung, knobs) survives crash-recovery rebuilds.

The ladder/admission units drive an injected clock — no sleeping; the
server drills use the same tiny CPU model as test_server.py.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.faults import FaultInjector
from jax_llama_tpu.overload import (
    OverloadController,
    open_loop_flood,
    poisson_schedule,
    summarize_flood,
)
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

pytestmark = pytest.mark.overload

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=256, dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _controller(clock, **kw):
    kw.setdefault("dwell_s", 1.0)
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("signal_window_s", 5.0)
    kw.setdefault("min_signal_samples", 2)
    return OverloadController(clock=clock, **kw)


def _miss(c, n=4):
    for _ in range(n):
        c.note_slo("interactive", False, True, False)


def _entry(priority="interactive", cost=10, deadline=None,
           disconnected=False):
    return types.SimpleNamespace(
        priority=priority, cost_tokens=cost, deadline=deadline,
        disconnected=disconnected,
    )


# ---------------------------------------------------------------------------
# Ladder state machine (injected clock, no server)
# ---------------------------------------------------------------------------

def test_ladder_escalates_with_dwell_and_one_rung_at_a_time():
    clock = Clock()
    c = _controller(clock)
    _miss(c)
    # Pressure just started: the dwell must elapse first.
    assert c.tick() is None
    assert c.rung == "normal"
    clock.advance(0.5)
    _miss(c)
    assert c.tick() is None  # 0.5s < dwell_s=1
    clock.advance(0.6)
    _miss(c)
    assert c.tick() == ("normal", "elevated")
    # The dwell re-arms after each transition — no straight-to-shed.
    assert c.tick() is None
    for expect in ("brownout-1", "brownout-2", "shed"):
        clock.advance(1.1)
        _miss(c)
        old, new = c.tick()
        assert new == expect
    # Top rung: sustained pressure holds, never overflows.
    clock.advance(1.1)
    _miss(c)
    assert c.tick() is None
    assert c.rung == "shed"


def test_ladder_recovers_after_cooldown_and_reports_knobs():
    clock = Clock()
    c = _controller(clock, batch_max_new=64, demote_blocks=8)
    c.force_rung("shed")
    kn = c.knobs()
    assert kn.shed_batch and not kn.admit_batch
    assert kn.prefill_budget_scale == 0.25
    assert kn.batch_max_new_cap == 16  # 64 halved twice past brownout-1
    # Old misses age out of the signal window -> calm; each recovery
    # step needs its own cooldown (hysteresis in time).
    _miss(c)
    clock.advance(6.0)  # > signal_window_s: samples gone
    assert c.tick() is None  # calm begins; cooldown not yet elapsed
    for expect in ("brownout-2", "brownout-1", "elevated", "normal"):
        clock.advance(2.1)
        old, new = c.tick()
        assert new == expect
    clock.advance(2.1)
    assert c.tick() is None  # at normal: nothing below to step to
    assert c.knobs().prefill_budget_scale == 1.0
    assert c.transitions_total == 4


def test_ladder_hysteresis_band_holds_the_rung():
    clock = Clock()
    c = _controller(clock, enter_attainment=0.80, exit_attainment=0.95)
    c.force_rung("elevated")
    # Attainment 0.9: above enter (no pressure), below exit (not
    # calm) — the band.  The rung must hold however long it lasts.
    for _ in range(20):
        for _ in range(9):
            c.note_slo("interactive", True, True, True)
        c.note_slo("interactive", False, True, False)
        clock.advance(3.0)
        assert c.tick() is None
    assert c.rung == "elevated"


def test_ladder_queue_wait_pressure_escalates():
    clock = Clock()
    c = _controller(clock, queue_wait_ms=100.0)
    for _ in range(4):
        c.observe_queue_wait(500.0)  # p90 far above the bar
    assert c.tick() is None  # pressure starts; dwell not yet elapsed
    clock.advance(1.1)
    for _ in range(4):
        c.observe_queue_wait(500.0)
    assert c.tick() == ("normal", "elevated")


def test_bad_hysteresis_config_refused():
    with pytest.raises(ValueError):
        OverloadController(enter_attainment=0.9, exit_attainment=0.8)


# ---------------------------------------------------------------------------
# Admission: deadline proof, backlog backstop, class gate
# ---------------------------------------------------------------------------

def test_admission_deadline_refusal_needs_evidence():
    clock = Clock()
    c = _controller(clock, max_queue=100)
    # No throughput evidence: a refusal must be provable, never
    # guessed — everything admits.
    assert c.admit("interactive", 10**6, 0.001, depth=0) is None
    # The admitted request lands in a queue and is then submitted
    # (push + pop release its backlog footprint, as the loop would).
    c.push(_entry("interactive", cost=10**6))
    assert c.pop() is not None
    # 1000 tokens/s observed prefill throughput.
    c.on_dispatch({"kind": "fused", "prefill_tokens": 1000,
                   "wall_ms": 1000.0, "k": 1, "occupancy": 1})
    r = c.admit("interactive", 10_000, 5.0, depth=0)
    assert r is not None and r.kind == "deadline"
    assert r.retry_after_s >= 1
    assert "timeout_s" in r.reason
    # The same prompt with a meetable deadline admits.
    assert c.admit("interactive", 10_000, 20.0, depth=0) is None
    # No timeout_s -> no deadline to prove against.
    assert c.admit("interactive", 10**6, None, depth=0) is None
    assert c.refused_deadline_total == 1


def test_admission_deadline_sees_inflight_admissions():
    """Admitted requests still in transit through the server inbox
    (admit() ran, the loop has not yet drained them into a class
    queue) must count toward the next request's backlog estimate —
    a one-dispatch-long burst is exactly the overload window."""
    c = _controller(Clock())
    c.on_dispatch({"kind": "fused", "prefill_tokens": 1000,
                   "wall_ms": 1000.0, "k": 1, "occupancy": 1})
    for _ in range(5):
        assert c.admit("interactive", 2000, 60.0, depth=0) is None
    # The sixth sees the burst's 10k in-flight tokens: est ~12 s.
    r = c.admit("interactive", 2000, 5.0, depth=0)
    assert r is not None and r.kind == "deadline"
    # Draining the inbox into the queues releases the reservations
    # (the tokens move to the queued footprint, then pop clears it).
    for _ in range(5):
        c.push(_entry("interactive", cost=2000))
    while c.pop() is not None:
        pass
    assert c.admit("interactive", 2000, 5.0, depth=0) is None


def test_admission_deadline_counts_backlog_ahead():
    clock = Clock()
    c = _controller(clock)
    c.on_dispatch({"kind": "fused", "prefill_tokens": 1000,
                   "wall_ms": 1000.0, "k": 1, "occupancy": 1})
    # 4000 interactive tokens queued ahead: a batch request sees them
    # all; its own 100 tokens alone would be fine.
    for _ in range(4):
        c.push(_entry("interactive", cost=1000))
    assert c.admit("batch", 100, 2.0, depth=4) is not None
    assert c.admit("batch", 100, 10.0, depth=4) is None
    c.push(_entry("batch", cost=100))  # the admitted batch request
    # Interactive-first ordering means interactive backlog only sees
    # the interactive queue — batch tokens ahead are irrelevant to it.
    c.push(_entry("batch", cost=50_000))
    assert c.admit("interactive", 100, 6.0, depth=6) is None


def test_admission_backlog_backstop_applies_even_when_disabled():
    c = OverloadController(enabled=False, max_queue=4)
    r = c.admit("interactive", 1, None, depth=4)
    assert r is not None and r.kind == "backlog"
    assert r.retry_after_s >= 1
    assert "overloaded" in r.reason
    # Disabled controller: no ladder, no deadline proof.
    assert c.tick() is None
    assert c.admit("batch", 10**6, 0.001, depth=0) is None


def test_admission_class_gate_at_brownout_2():
    clock = Clock()
    c = _controller(clock)
    c.force_rung("brownout-2")
    r = c.admit("batch", 10, None, depth=0)
    assert r is not None and r.kind == "class"
    # Interactive is the protected class — admitted at every rung.
    c.force_rung("shed")
    assert c.admit("interactive", 10, None, depth=0) is None
    assert c.refused_batch_total == 1


def test_retry_after_is_load_derived():
    clock = Clock()
    c = _controller(clock)
    c.on_dispatch({"kind": "insert", "prefill_tokens": 1000,
                   "wall_ms": 1000.0, "k": 1, "occupancy": 1})
    for _ in range(10):
        c.push(_entry("batch", cost=1000))
    # 10k tokens of backlog at 1k tokens/s -> ~10s (+1 rounding).
    assert 10 <= c.retry_after_s() <= 12
    # And it caps at 60 however deep the backlog.
    for _ in range(100):
        c.push(_entry("batch", cost=10_000))
    assert c.retry_after_s() == 60


# ---------------------------------------------------------------------------
# Queues: ordering, shedding, reaping
# ---------------------------------------------------------------------------

def test_disabled_controller_is_plain_fifo():
    """priority_classes=off must be the genuinely pre-ladder behavior:
    one queue, arrival order — not interactive-first in disguise (the
    bench harness's static A/B arm depends on this)."""
    c = OverloadController(enabled=False, max_queue=100)
    b1, i1, b2 = _entry("batch"), _entry("interactive"), _entry("batch")
    for e in (b1, i1, b2):
        c.push(e)
    assert [c.pop() for _ in range(3)] == [b1, i1, b2]


def test_queue_strict_interactive_first_fifo_within_class():
    c = _controller(Clock())
    b1, b2 = _entry("batch"), _entry("batch")
    i1, i2 = _entry("interactive"), _entry("interactive")
    for e in (b1, b2, i1, b_last := _entry("batch"), i2):
        c.push(e)
    assert [c.pop() for _ in range(5)] == [i1, i2, b1, b2, b_last]
    assert c.pop() is None


def test_shed_batch_only_at_shed_rung_and_only_batch():
    c = _controller(Clock())
    b1, b2, i1 = _entry("batch"), _entry("batch"), _entry("interactive")
    for e in (b1, i1, b2):
        c.push(e)
    assert c.shed_batch() == []  # normal rung: nothing shed
    c.force_rung("shed")
    assert c.shed_batch() == [b1, b2]
    assert c.sheds_total == 2
    assert c.pop() is i1  # interactive untouched
    assert c.queued_total() == 0


def test_reap_pulls_expired_and_disconnected():
    clock = Clock(100.0)
    c = _controller(clock)
    live = _entry("interactive", deadline=200.0)
    dead = _entry("interactive", deadline=99.0)
    gone = _entry("batch", disconnected=True)
    for e in (live, dead, gone):
        c.push(e)
    expired, disconnected = c.reap()
    assert expired == [dead] and disconnected == [gone]
    assert c.pop() is live and c.queued_total() == 0


def test_drain_all_empties_every_class():
    c = _controller(Clock())
    entries = [_entry("batch"), _entry("interactive"), _entry("batch")]
    for e in entries:
        c.push(e)
    assert set(map(id, c.drain_all())) == set(map(id, entries))
    assert c.queued_total() == 0


# ---------------------------------------------------------------------------
# Poisson schedule
# ---------------------------------------------------------------------------

def test_poisson_schedule_rate_and_determinism():
    a = poisson_schedule(100.0, 10.0, seed=7)
    b = poisson_schedule(100.0, 10.0, seed=7)
    assert a == b  # seeded -> reproducible sweeps
    assert a == sorted(a) and all(0 <= t < 10.0 for t in a)
    # ~1000 arrivals, 4 sigma tolerance (sigma = sqrt(1000) ~ 32).
    assert 870 <= len(a) <= 1130
    assert poisson_schedule(0.0, 10.0) == []
    assert poisson_schedule(10.0, 0.0) == []


# ---------------------------------------------------------------------------
# Server integration (tiny CPU model)
# ---------------------------------------------------------------------------

def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def test_http_priority_validation_and_batch_cap(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    with LLMServer(cb, brownout_batch_max_new=4) as srv:
        # Junk priority is the client's defect: 400, not a silent
        # default.
        for junk in ("urgent", 3, [], {"a": 1}):
            try:
                _post(srv.address, {"prompt": [1, 2], "priority": junk})
                assert False, f"expected 400 for priority={junk!r}"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "priority" in json.loads(e.read())["error"]
        # Valid classes admit; at brownout-1 the batch budget clamps
        # to the cap while interactive is untouched.
        srv.overload.force_rung("brownout-1")
        s, body, _ = _post(
            srv.address,
            {"prompt": [1, 2, 3], "max_new_tokens": 10,
             "priority": "batch"},
        )
        assert s == 200 and len(body["tokens"]) == 4  # capped
        s, body, _ = _post(
            srv.address,
            {"prompt": [1, 2, 3], "max_new_tokens": 10,
             "priority": "interactive"},
        )
        assert s == 200 and len(body["tokens"]) == 10
        srv.overload.force_rung("normal")


def test_http_batch_refused_at_brownout_2_with_retry_after(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    with LLMServer(cb) as srv:
        srv.overload.force_rung("brownout-2")
        try:
            _post(srv.address,
                  {"prompt": [1, 2], "max_new_tokens": 2,
                   "priority": "batch"})
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) >= 1
            assert "batch" in json.loads(e.read())["error"]
        # Interactive still served at the same rung.
        s, body, _ = _post(
            srv.address,
            {"prompt": [1, 2], "max_new_tokens": 2,
             "priority": "interactive"},
        )
        assert s == 200 and len(body["tokens"]) == 2
        srv.overload.force_rung("normal")


def test_http_priority_inversion_interactive_admits_first(model):
    """A full batch backlog is queued behind a busy slot; a later
    interactive request must be admitted (and finish) ahead of it."""
    params, config = model
    # A 20 ms injected delay per step dispatch pins the resident in
    # its slot for ~2 s — the tiny model alone decodes too fast to
    # sequence the queue deterministically.
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=256,
        fault_injector=FaultInjector("step~1.0:delay=0.02"),
    )
    with LLMServer(cb) as srv:
        # Warm the compile caches so queue residency, not compilation,
        # dominates the timeline below.
        _post(srv.address, {"prompt": [9, 9], "max_new_tokens": 2})

        done_at = {}
        threads = []

        def call(name, payload):
            def run():
                _post(srv.address, payload, timeout=300)
                done_at[name] = time.monotonic()
            t = threading.Thread(target=run)
            t.start()
            threads.append(t)

        # Occupy the single slot long enough to stack the queue.
        call("resident", {"prompt": [3, 4], "max_new_tokens": 100})
        time.sleep(0.4)  # resident admitted, slot busy
        for j in range(3):
            call(f"batch{j}", {"prompt": [5 + j, 6], "max_new_tokens": 2,
                               "priority": "batch"})
        time.sleep(0.2)  # batch backlog queued (free slots = 0)
        call("inter", {"prompt": [8, 8], "max_new_tokens": 2,
                       "priority": "interactive"})
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        assert done_at["inter"] < min(
            done_at[f"batch{j}"] for j in range(3)
        ), f"interactive finished after batch backlog: {done_at}"


def test_http_queued_batch_shed_cleanly_with_retry_after(model):
    """A batch request already queued behind a busy slot is shed when
    the ladder reaches 'shed': a clean 503 + Retry-After, never a
    hang — including for a STREAMING client, which gets a real 503
    status because no token ever flowed."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=256,
        fault_injector=FaultInjector("step~1.0:delay=0.02"),
    )
    with LLMServer(cb) as srv:
        _post(srv.address, {"prompt": [9, 9], "max_new_tokens": 2})
        results = {}
        threads = []

        def call(name, payload):
            def run():
                try:
                    results[name] = _post(srv.address, payload,
                                          timeout=120)
                except urllib.error.HTTPError as e:
                    results[name] = (
                        e.code, json.loads(e.read()), dict(e.headers)
                    )
                except Exception as e:  # surface in the assert below
                    results[name] = (-1, {"error": repr(e)}, {})
            t = threading.Thread(target=run)
            t.start()
            threads.append(t)

        call("resident", {"prompt": [3, 4], "max_new_tokens": 100})
        time.sleep(0.4)
        call("blocking", {"prompt": [5, 6], "max_new_tokens": 2,
                          "priority": "batch"})
        call("streaming", {"prompt": [6, 7], "max_new_tokens": 2,
                           "priority": "batch", "stream": True})
        time.sleep(0.3)  # both queued (slot busy)
        srv.overload.force_rung("shed")
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)  # nobody hangs
        assert results["resident"][0] == 200  # in-flight untouched
        for name in ("blocking", "streaming"):
            code, body, headers = results[name]
            assert code == 503, (name, results[name])
            assert "shed" in body["error"]
            assert int(headers["Retry-After"]) >= 1
        srv.overload.force_rung("normal")


def test_controller_state_survives_crash_recovery(model):
    """A crash-recovery rebuild must keep the controller's rung AND
    re-apply its knobs to the fresh batcher (which starts from the
    base ctor's prefill budget)."""
    params, config = model
    inj = FaultInjector("step@2:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16,
        prefill_budget=16, fault_injector=inj,
    )
    with LLMServer(cb) as srv:
        srv.overload.force_rung("brownout-2")
        srv.overload.transitions_total = 3
        srv._apply_overload_knobs()
        assert srv.batcher.prefill_budget == 4  # 16 * 0.25
        # The 2nd step dispatch faults -> rebuild + replay; the
        # request still completes.
        s, body, _ = _post(
            srv.address, {"prompt": [1, 2, 3], "max_new_tokens": 6}
        )
        assert s == 200 and len(body["tokens"]) == 6
        assert srv.recoveries_total == 1
        # Controller state intact, knobs re-applied post-rebuild.
        assert srv.overload.rung == "brownout-2"
        assert srv.overload.transitions_total == 3
        assert srv.batcher.prefill_budget == 4
        srv.overload.force_rung("normal")


def _flood_server(params, config, **ctl_kw):
    """A tiny server + drill-scale controller for the flood tests."""
    from jax_llama_tpu.obs import Observability

    slo = ctl_kw.pop("slo_ttft_ms", 150.0)
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        obs=Observability(slo_ttft_ms=slo),
    )
    ctl = OverloadController(
        enabled=True, max_queue=ctl_kw.pop("max_queue", 8),
        slo_ttft_ms=slo, dwell_s=0.05, cooldown_s=0.2,
        signal_window_s=2.0, min_signal_samples=2, **ctl_kw,
    )
    return LLMServer(cb, overload=ctl)


def _run_flood(srv, n, rate_hz, seed=0):
    sched = poisson_schedule(rate_hz, n / rate_hz, seed=seed)[:n]

    def payload_fn(i):
        if i % 2 == 0:
            return {"prompt": [1 + i % 60, 2], "max_new_tokens": 3,
                    "priority": "interactive", "stream": True,
                    "timeout_s": 20.0}
        return {"prompt": list(range(1, 33)), "max_new_tokens": 8,
                "priority": "batch", "stream": True, "timeout_s": 20.0}

    return open_loop_flood(
        srv.address, sched, payload_fn, timeout_s=60.0,
        join_timeout_s=120.0,
    )


# slow (r17 budget rebalance, ~13 s): tier-1 keeps an open-loop Poisson
# flood via test_flood_escalates_ladder_then_recovers_to_normal and the
# 503/Retry-After well-formedness pin via
# test_http_queued_batch_shed_cleanly_with_retry_after; this zero-hangs
# flood joins the slow acceptance drill below (`make overload` runs
# the file unfiltered).
@pytest.mark.slow
def test_flood_drill_zero_hangs_all_503s_well_formed(model):
    """The flood drill: an open-loop Poisson mixed-class flood
    against a 2-slot server with a depth-8 backstop.  Every client
    gets a terminal outcome (zero hangs), every refusal is a 503
    carrying Retry-After, and the server still serves afterwards."""
    params, config = model
    with _flood_server(params, config) as srv:
        # Warm the compile caches (both request shapes).
        _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 3})
        _post(srv.address,
              {"prompt": list(range(1, 33)), "max_new_tokens": 8})
        records = _run_flood(srv, n=30, rate_hz=30.0)
        summary = summarize_flood(records, slo_ttft_ms=150.0)
        assert summary["hung_total"] == 0, summary
        statuses = {r["status"] for r in records}
        assert statuses <= {200, 503, 504}, statuses
        for cls in ("interactive", "batch"):
            s = summary[cls]
            assert s["errors"] == 0, (cls, s)
            assert s["refused_503"] == s["refused_with_retry_after"], (
                cls, s,
            )
        assert sum(
            summary[c]["served"]
            for c in ("interactive", "batch")
        ) > 0
        # The server is healthy after the flood: a fresh request works.
        s, body, _ = _post(
            srv.address, {"prompt": [7, 7], "max_new_tokens": 2}
        )
        assert s == 200 and len(body["tokens"]) == 2


def test_flood_escalates_ladder_then_recovers_to_normal(model):
    """Sustained overload escalates the ladder (visible in /healthz +
    /metrics + the structured annotation ring); once the flood stops,
    the ladder steps back down to normal — hysteresis proven end to
    end, not just in the clock-injected unit."""
    params, config = model
    # An unmeetable TTFT SLO (0.01 ms) makes every served request a
    # miss — deterministic pressure without timing sensitivity.
    with _flood_server(params, config, slo_ttft_ms=0.01) as srv:
        _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 3})
        _run_flood(srv, n=16, rate_hz=20.0)
        deadline = time.monotonic() + 60.0
        seen_elevated = False
        while time.monotonic() < deadline:
            rung = srv.overload.rung
            if rung != "normal":
                seen_elevated = True
                break
            time.sleep(0.05)
        assert seen_elevated, "ladder never escalated under the flood"
        with urllib.request.urlopen(srv.address + "/healthz") as r:
            h = json.loads(r.read())
        assert h["overload"]["rung"] != "normal"
        assert h["overload"]["enabled"] is True
        # Escalations are annotated into the obs event ring.
        assert any(
            e["name"] == "overload_transition"
            for e in list(srv.obs.events)
        )
        # Flood over: the signal window drains (2 s) and the ladder
        # walks back down one cooldown (0.2 s) per rung.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if srv.overload.rung == "normal":
                break
            time.sleep(0.1)
        assert srv.overload.rung == "normal", (
            "ladder failed to recover after the flood: "
            f"{srv.overload.health()}"
        )
        # /metrics carries the story: transitions happened, the rung
        # gauge is back at 0.
        with urllib.request.urlopen(srv.address + "/metrics") as r:
            text = r.read().decode()
        lines = dict(
            ln.split(" ", 1) for ln in text.splitlines()
            if ln and not ln.startswith("#")
        )
        assert float(lines["llm_overload_rung"]) == 0.0
        assert float(lines["llm_overload_transitions_total"]) >= 2


@pytest.mark.slow
def test_acceptance_drill_interactive_held_at_2x_sustainable(model):
    """The acceptance drill (ISSUE 9): a Poisson mixed-class flood at
    >= 2x the measured sustainable rate.  With the ladder + priority
    classes on: interactive TTFT SLO attainment stays >= 0.5 while
    batch is refused/shed; every refused/shed request receives a
    well-formed 503 + Retry-After; zero hung clients; and the ladder
    steps back down to normal after the flood."""
    params, config = model
    with _flood_server(params, config, slo_ttft_ms=2000.0) as srv:
        _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 3})
        _post(srv.address,
              {"prompt": list(range(1, 33)), "max_new_tokens": 8})
        # Sustainable rate: a closed-loop burst of 8 mixed requests.
        t0 = time.monotonic()
        _run_flood(srv, n=8, rate_hz=1000.0, seed=3)
        sustainable = 8.0 / (time.monotonic() - t0)

    with _flood_server(params, config, slo_ttft_ms=2000.0) as srv:
        _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 3})
        _post(srv.address,
              {"prompt": list(range(1, 33)), "max_new_tokens": 8})
        rate = max(2.0 * sustainable, 4.0)
        records = _run_flood(srv, n=60, rate_hz=rate, seed=4)
        summary = summarize_flood(records, slo_ttft_ms=2000.0)
        assert summary["hung_total"] == 0, summary
        ia = summary["interactive"]["slo_attainment"]
        assert ia is not None and ia >= 0.5, summary
        # Batch pays: refused (backlog/class) or shed or slower.
        b = summary["batch"]
        assert b["refused_503"] == b["refused_with_retry_after"]
        i = summary["interactive"]
        assert i["refused_503"] == i["refused_with_retry_after"]
        # The ladder moved under the flood (backlog pressure) and
        # recovers afterwards.
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            if srv.overload.rung == "normal":
                break
            time.sleep(0.1)
        assert srv.overload.rung == "normal", srv.overload.health()
