"""Data pipeline: packing, masking, determinism, mesh sharding, and an
end-to-end train step fed from the loader."""

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params, make_mesh
from jax_llama_tpu.data import Batch, batches, pack_documents, shard_batch
from jax_llama_tpu.parallel import shard_params
from jax_llama_tpu.train import init_train_state, make_optimizer, train_step


def test_pack_concatenates_and_pads():
    docs = [[1, 2, 3], [4, 5], [6]]
    rows = list(pack_documents(docs, seq_len=4, pad_id=0))
    assert [r.tokens.tolist() for r in rows] == [[1, 2, 3, 4], [5, 6, 0, 0]]
    assert rows[0].loss_mask.all()
    # last real position's target is padding -> masked; padding masked.
    assert rows[1].loss_mask.tolist() == [True, False, False, False]


def test_partial_row_last_target_receives_loss():
    """Convention regression (ADVICE r1): data.py's query-indexed mask and
    lm_loss's consumption must agree, so the last real target of a partial
    row ('6' below, predicted from position of '5') contributes loss."""
    import jax.numpy as jnp
    from jax_llama_tpu.train import lm_loss

    docs = [[1, 2, 3], [4, 5], [6]]
    rows = list(pack_documents(docs, seq_len=4, pad_id=0))
    partial = rows[1]  # tokens [5, 6, 0, 0], mask [T, F, F, F]
    config = get_config(
        "tiny", vocab_size=8, dim=16, n_layers=1, n_heads=2, n_kv_heads=2,
        multiple_of=16, max_seq_len=4,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    tokens = jnp.asarray(partial.tokens)[None]
    mask = jnp.asarray(partial.loss_mask)[None]

    base = lm_loss(params, tokens, config, mask)
    # Perturb only the '6' target's ground truth: if that term is in the
    # loss, changing the token at its *target* position changes the loss.
    toks2 = tokens.at[0, 1].set(7)
    changed = lm_loss(params, toks2, config, mask)
    assert not np.isclose(float(base), float(changed)), (
        "the partial row's last real target is excluded from the loss"
    )
    # Exactly one term is active: the masked mean equals the NLL of
    # target '6' predicted from query position 0.
    from jax_llama_tpu.models import forward

    logits, _ = forward(
        params, tokens, jnp.arange(4)[None, :], config
    )
    logp = jax.nn.log_softmax(np.asarray(logits, np.float64), axis=-1)
    want = -logp[0, 0, int(tokens[0, 1])]
    np.testing.assert_allclose(float(base), want, rtol=1e-5)


def test_pack_long_document_spans_rows():
    rows = list(pack_documents([list(range(10))], seq_len=4, pad_id=99))
    assert [r.tokens.tolist() for r in rows] == [
        [0, 1, 2, 3], [4, 5, 6, 7], [8, 9, 99, 99]
    ]


def test_pack_rejects_tiny_seq():
    with pytest.raises(ValueError):
        list(pack_documents([[1]], seq_len=1))


def test_batches_shapes_and_remainder():
    docs = [[i] * 5 for i in range(7)]  # 35 tokens -> 8 rows of 4 + rem
    got = list(batches(docs, batch_size=4, seq_len=4, drop_remainder=True))
    assert all(b.tokens.shape == (4, 4) for b in got)
    got_pad = list(batches(docs, batch_size=4, seq_len=4, drop_remainder=False))
    assert len(got_pad) > len(got)
    last = got_pad[-1]
    assert last.tokens.shape == (4, 4)
    assert not last.loss_mask[-1].any()  # padded filler rows carry no loss


def test_shuffle_deterministic():
    docs = [[i] * 4 for i in range(32)]
    a = [b.tokens.tolist() for b in batches(docs, 2, 4, seed=7, shuffle_buffer=8)]
    b_ = [b.tokens.tolist() for b in batches(docs, 2, 4, seed=7, shuffle_buffer=8)]
    c = [b.tokens.tolist() for b in batches(docs, 2, 4, seed=8, shuffle_buffer=8)]
    assert a == b_
    assert a != c  # different seed reorders (overwhelmingly likely)


def test_shard_batch_places_on_mesh():
    mesh = make_mesh(data=2, tensor=2, devices=jax.devices()[:4])
    batch = Batch(
        tokens=np.zeros((4, 8), np.int32),
        loss_mask=np.ones((4, 8), bool),
    )
    sharded = shard_batch(batch, mesh)
    assert sharded.tokens.sharding.is_equivalent_to(
        jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("data", "fsdp"), None)
        ),
        2,
    )


def test_loader_feeds_train_step():
    config = get_config(
        "tiny", vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=16,
    )
    mesh = make_mesh(data=2, devices=jax.devices()[:2])
    params = shard_params(
        init_params(jax.random.PRNGKey(0), config), mesh, config
    )
    opt = make_optimizer(1e-3)
    state = init_train_state(params, opt)
    rng = np.random.RandomState(0)
    docs = [rng.randint(1, 64, size=rng.randint(4, 30)).tolist() for _ in range(20)]
    n = 0
    for batch in batches(docs, batch_size=2, seq_len=16):
        batch = shard_batch(batch, mesh)
        state, loss = train_step(
            state, batch.tokens, config, opt,
            loss_mask=batch.loss_mask, mesh=mesh,
        )
        assert np.isfinite(float(loss))
        n += 1
    assert n >= 1
