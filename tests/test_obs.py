"""Observability layer (obs.py): request span timelines through the
admission state machine (classic / fused / spec / restoring), dispatch
spans causally linked to the requests they carried, Prometheus
histogram bucket math, SLO accounting, and the Chrome/Perfetto
``trace_event`` export schema.

The unit tests drive :class:`Observability` with an injectable clock;
the integration tests run the real tiny-model ``ContinuousBatcher`` and
assert the timelines the serving loop recorded — including the
acceptance-criterion drill: a request served through a FUSED admission
after a radix host-tier RESTORE owns a queued/restoring/prefilling/
decoding timeline whose span links resolve to real dispatch spans, and
the whole window exports as loadable ``trace_event`` JSON."""

import json

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.obs import (
    HISTOGRAMS,
    LABELED_HISTOGRAMS,
    METRICS,
    CostModelCache,
    Histogram,
    Observability,
    StructuredLogger,
    metric_meta,
)
from jax_llama_tpu.serving import ContinuousBatcher

pytestmark = pytest.mark.obs

BS = 16  # block size for the tier drills (matches test_kvcache)

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ---------------------------------------------------------------------------
# Histogram bucket math
# ---------------------------------------------------------------------------

def test_histogram_bucket_math():
    h = Histogram("x_ms", "help", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 5.0, 7.0):
        h.observe(v)
    # le is LESS-THAN-OR-EQUAL: a value on a bound lands in that bucket.
    assert h.cumulative() == [
        ("1", 2), ("2", 3), ("5", 4), ("+Inf", 5),
    ]
    assert h.count == 5
    assert h.sum == pytest.approx(15.0)


def test_histogram_exposition_format():
    h = Histogram("lat_ms", "latency help", buckets=(10.0, 100.0))
    h.observe(3.0)
    h.observe(250.0)
    lines = h.expose("llm_")
    assert lines[0] == "# HELP llm_lat_ms latency help"
    assert lines[1] == "# TYPE llm_lat_ms histogram"
    assert 'llm_lat_ms_bucket{le="10"} 1' in lines
    assert 'llm_lat_ms_bucket{le="+Inf"} 2' in lines
    assert "llm_lat_ms_sum 253.0" in lines
    assert "llm_lat_ms_count 2" in lines
    # The +Inf bucket always equals _count (Prometheus invariant).
    inf = [ln for ln in lines if 'le="+Inf"' in ln][0]
    cnt = [ln for ln in lines if ln.endswith("_count 2")][0]
    assert inf.rsplit(" ", 1)[1] == cnt.rsplit(" ", 1)[1]


def test_histogram_rejects_unsorted_buckets():
    with pytest.raises(ValueError):
        Histogram("bad", "h", buckets=(5.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", "h", buckets=(1.0, 1.0, 2.0))


def test_metric_registry_shape():
    """Every registered metric carries a valid type and a non-empty
    HELP; the names the exposition derives families from are covered."""
    for name, (kind, help_text) in METRICS.items():
        assert kind in ("counter", "gauge"), name
        assert help_text, name
    assert metric_meta("emitted_tokens_total") == METRICS[
        "emitted_tokens_total"
    ]
    assert metric_meta("definitely_not_registered") is None
    # radix_nodes_total is the deliberate counter-convention exception.
    assert METRICS["radix_nodes_total"][0] == "gauge"
    assert set(HISTOGRAMS) == {
        "ttft_ms", "itl_ms", "queue_wait_ms", "prefill_chunk_ms",
        "swap_in_ms", "compile_ms", "dispatch_ms",
        "prefix_hit_depth_tokens", "session_kv_blocks",
    }
    # dispatch_ms renders as one labeled series per dispatch kind.
    assert LABELED_HISTOGRAMS == {"dispatch_ms"}
    # The labeled attribution families are registered too.
    for fam in ("mxu_utilization", "hbm_utilization",
                "host_overhead_ratio", "jit_cache_entries",
                "program_compiles_total", "compiles_total"):
        assert metric_meta(fam) is not None, fam


# ---------------------------------------------------------------------------
# Span lifecycle / binding / rings (fake clock)
# ---------------------------------------------------------------------------

def test_span_lifecycle_and_dispatch_links():
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.request_queued(7, prompt_tokens=12)
    clk.advance(0.050)
    obs.begin_span(7, "prefilling")
    seq = obs.record_dispatch(
        kind="insert", k=1, occupancy=1, prefill_tokens=12,
        wall_ms=5.0, fetch_ms=1.0, rids=[7],
    )
    clk.advance(0.010)
    obs.begin_span(7, "decoding")
    seq2 = obs.record_dispatch(kind="decode", k=4, occupancy=1,
                               wall_ms=2.0, rids=[7])
    clk.advance(0.008)
    obs.request_end(7, "finished")

    obs.bind(7, "ext-abc")
    tl = obs.timeline_json("ext-abc")
    assert tl is not None
    assert tl["request_id"] == "ext-abc" and tl["rids"] == [7]
    assert tl["prompt_tokens"] == 12
    assert tl["outcome"] == "finished" and tl["error"] is None
    states = [sp["state"] for sp in tl["spans"]]
    assert states == ["queued", "prefilling", "decoding"]
    q, pf, dec = tl["spans"]
    assert q["duration_ms"] == pytest.approx(50.0)
    assert pf["dispatches"] == [seq]
    assert dec["dispatches"] == [seq2]
    # Every linked seq resolves to a real record in the payload.
    linked = {d["seq"] for d in tl["dispatch_spans"]}
    assert linked == {seq, seq2}
    # The queued->prefilling edge fed the queue-wait histogram.
    assert obs.hist["queue_wait_ms"].count == 1
    assert obs.hist["queue_wait_ms"].sum == pytest.approx(50.0)
    # dispatch_ms saw both (one per-kind series each);
    # prefill_chunk_ms only the insert.
    assert obs.hist_dispatch["insert"].count == 1
    assert obs.hist_dispatch["decode"].count == 1
    assert obs.hist["prefill_chunk_ms"].count == 1
    # Lookup also works by provisional id and bare rid.
    assert obs.timeline_json("7")["request_id"] == "ext-abc"


def test_bind_before_spans_and_unknown_rid_is_noop():
    obs = Observability(clock=FakeClock())
    obs.bind(99, "never-queued")  # unknown rid: no crash, no timeline
    assert obs.timeline_json("never-queued") is None
    obs.begin_span(42, "decoding")  # unknown rid: no-op
    obs.request_end(42, "finished")
    assert obs.requests_json()["requests"] == []


def test_bind_replay_folds_into_existing_timeline():
    """Crash-recovery replay: the fresh rid (and its queued span) fold
    into the external id's existing timeline — one continuous story."""
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.request_queued(1, 8)
    obs.bind(1, "cli-id")
    obs.begin_span(1, "decoding")
    clk.advance(0.010)
    # crash: replay resubmits under a fresh rid
    obs.request_queued(2, 8)
    obs.bind(2, "cli-id", replay=True)
    clk.advance(0.005)
    obs.begin_span(2, "decoding")
    obs.request_end(2, "finished")
    tl = obs.timeline_json("cli-id")
    assert tl["rids"] == [1, 2]
    assert tl["outcome"] == "finished"
    states = [sp["state"] for sp in tl["spans"]]
    assert states == ["queued", "decoding", "queued", "decoding"]
    assert tl["spans"][2]["note"] == "replay"
    # The rid-2 lookups now resolve to the folded timeline too.
    assert obs.timeline_json("2")["request_id"] == "cli-id"


def test_bind_id_collision_keeps_separate_timelines():
    """A NON-replay bind onto an id another request owns (a client
    reusing X-Request-Id) must not merge the two: the live timeline
    keeps its state, the new request stays addressable by rid."""
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.request_queued(1, 4)
    obs.bind(1, "reused-id")
    obs.begin_span(1, "decoding")
    obs.request_queued(2, 9)  # different request, same client id
    obs.bind(2, "reused-id")
    tl = obs.timeline_json("reused-id")
    assert tl["rids"] == [1] and tl["prompt_tokens"] == 4
    tl2 = obs.timeline_json("2")
    assert tl2["request_id"] == "r2" and tl2["prompt_tokens"] == 9
    obs.request_end(1, "finished")
    assert obs.timeline_json("reused-id")["outcome"] == "finished"


def test_bind_replay_rid_index_bounded():
    """Folded replay rids are capped: only the most recent
    incarnations stay in the by-rid index (a crash-looping request
    cannot grow its timeline's index entries without bound)."""
    from jax_llama_tpu.obs import _MAX_RIDS

    obs = Observability(clock=FakeClock())
    obs.request_queued(0, 4)
    obs.bind(0, "storm")
    for rid in range(1, 3 * _MAX_RIDS):
        obs.request_queued(rid, 4)
        obs.bind(rid, "storm", replay=True)
    tl = obs.timeline_json("storm")
    assert len(tl["rids"]) == _MAX_RIDS
    assert tl["rids"][-1] == 3 * _MAX_RIDS - 1
    # Aged-out rids no longer resolve; recent ones do.
    assert obs.timeline_json("0") is None
    assert obs.timeline_json(str(3 * _MAX_RIDS - 1)) is not None


def test_timeline_lru_eviction_and_dispatch_ring_bound():
    obs = Observability(max_timelines=4, ring=8, clock=FakeClock())
    for rid in range(10):
        obs.request_queued(rid, 4)
    assert len(obs.requests_json(64)["requests"]) == 4
    assert obs.timeline_json("r0") is None          # evicted
    assert obs.timeline_json("r9") is not None      # newest retained
    for i in range(20):
        obs.record_dispatch(kind="decode", k=1, wall_ms=1.0)
    d = obs.dispatches_json(128)["dispatches"]
    assert len(d) == 8
    assert d[-1]["seq"] == 19  # seq is ring-global, not index
    # n <= 0 returns nothing, never the whole store ([-0:] trap).
    assert obs.dispatches_json(0)["dispatches"] == []
    assert obs.requests_json(-3)["requests"] == []


def test_timeline_eviction_prefers_terminal_over_live():
    """A long-running LIVE request must survive a burst of newer
    finished requests: terminal timelines evict first, so its
    request_end still lands (the finished counter never undercounts a
    request the server is actively serving)."""
    obs = Observability(max_timelines=4, clock=FakeClock())
    obs.request_queued(0, 4)            # the long-running stream
    obs.begin_span(0, "decoding")
    for rid in range(1, 10):            # newer, all finished
        obs.request_queued(rid, 4)
        obs.request_end(rid, "finished")
    assert obs.timeline_json("r0") is not None   # live: kept
    obs.request_end(0, "finished")
    assert obs.timeline_json("r0")["outcome"] == "finished"
    assert obs.requests_finished_total == 10
    # All-live pathology: the hard bound still holds.
    obs2 = Observability(max_timelines=3, clock=FakeClock())
    for rid in range(8):
        obs2.request_queued(rid, 4)
    assert len(obs2.requests_json(64)["requests"]) == 3


def test_slo_accounting_gauges_and_goodput():
    obs = Observability(slo_ttft_ms=100.0, slo_itl_ms=50.0,
                        clock=FakeClock())
    assert obs.slo_account(80.0, 40.0, tokens=10) is True
    assert obs.slo_account(150.0, 40.0, tokens=7) is False   # ttft miss
    assert obs.slo_account(80.0, 90.0, tokens=7) is False    # itl miss
    assert obs.slo_account(None, None, tokens=0) is False    # no token
    assert obs.slo_account(80.0, 40.0, tokens=9,
                           completed=False) is False         # failed
    m = obs.metrics()
    assert m["requests_slo_ok_total"] == 1
    assert m["goodput_tokens_total"] == 10
    # ttft passes rows 1,3 (the no-token row fails a configured TTFT);
    # itl passes rows 1,2,4 (no-token trivially passes ITL); the
    # completed=False row passes neither.
    assert m["slo_ttft_attainment"] == pytest.approx(2 / 5)
    assert m["slo_itl_attainment"] == pytest.approx(3 / 5)
    assert m["slo_attainment"] == pytest.approx(1 / 5)
    assert m["slo_ttft_ms"] == 100.0 and m["slo_itl_ms"] == 50.0


def test_slo_unconfigured_dimensions_always_pass():
    obs = Observability(clock=FakeClock())  # no SLOs set
    assert obs.slo_account(9999.0, 9999.0, tokens=5) is True
    assert obs.slo_account(None, None, tokens=3) is True
    m = obs.metrics()
    assert m["slo_attainment"] == 1.0
    assert m["goodput_tokens_total"] == 8  # == delivered tokens
    # One configured dimension scores independently of the other.
    obs2 = Observability(slo_itl_ms=50.0, clock=FakeClock())
    assert obs2.slo_account(99999.0, 10.0, tokens=1) is True
    assert obs2.slo_account(None, 90.0, tokens=1) is False


def test_request_rejected_records_terminal_timeline():
    """A pre-admission 504 (no batcher rid ever existed) still gets a
    terminal timeline under its external id and counts as failed, so
    the overload failure signals (/debug + requests_failed_total +
    SLO attainment) agree instead of contradicting."""
    obs = Observability(clock=FakeClock())
    obs.request_rejected("overload-1", "timed out before admission")
    tl = obs.timeline_json("overload-1")
    assert tl["outcome"] == "failed" and tl["rids"] == []
    assert tl["spans"][0]["state"] == "queued"
    assert tl["spans"][0]["end_ms"] is not None
    assert obs.requests_failed_total == 1
    # Id reuse keeps the existing (richer) record — but the failure
    # still COUNTS (every 504 the client saw is a failure).
    obs.request_queued(1, 4)
    obs.bind(1, "live-id")
    obs.request_rejected("live-id", "should not clobber")
    assert obs.timeline_json("live-id")["outcome"] is None
    assert obs.requests_failed_total == 2


def test_request_kv_merge_semantics_and_timeline_field():
    """Per-session KV accounting: gauge-like fields set-latest,
    ledger fields (swap bytes, evictions suffered) accumulate, and the
    merged dict rides /debug/requests/<id> as ``kv``."""
    obs = Observability(clock=FakeClock())
    obs.request_queued(1, prompt_tokens=64)
    obs.bind(1, "kv-req")
    obs.request_kv(1, blocks_held=4, prefix_hit_tokens=32)
    obs.request_kv(1, evictions_suffered=2)
    obs.request_kv(1, swap_in_bytes=1000, evictions_suffered=1)
    obs.request_kv(1, blocks_held=6)       # set-latest
    obs.request_kv(1, swap_in_bytes=500)   # accumulates
    tl = obs.timeline_json("kv-req")
    assert tl["kv"] == {
        "blocks_held": 6, "prefix_hit_tokens": 32,
        "evictions_suffered": 3, "swap_in_bytes": 1500,
    }
    # Unknown rid is a no-op, never a KeyError.
    obs.request_kv(99, blocks_held=1)
    # A timeline that never saw KV traffic exposes an empty dict.
    obs.request_queued(2, prompt_tokens=8)
    obs.bind(2, "kv-none")
    assert obs.timeline_json("kv-none")["kv"] == {}


def test_observe_kv_histograms_token_block_buckets():
    """prefix_hit_depth_tokens / session_kv_blocks are pow2 TOKEN and
    BLOCK histograms (not ms): 0-depth cold admissions land in the
    first bucket, the families render into the exposition."""
    obs = Observability(clock=FakeClock())
    obs.observe_kv(hit_depth_tokens=0)
    obs.observe_kv(hit_depth_tokens=32)
    obs.observe_kv(session_blocks=3)
    h = obs.hist["prefix_hit_depth_tokens"]
    assert h.buckets[0] == 1.0 and h.buckets[-1] == 16384.0
    assert h.count == 2
    cum = dict(h.cumulative())
    assert cum["1"] == 1 and cum["32"] == 2
    hb = obs.hist["session_kv_blocks"]
    assert hb.buckets[-1] == 1024.0 and hb.count == 1
    lines = obs.expose_histograms("llm_")
    assert any(
        ln.startswith("llm_prefix_hit_depth_tokens_bucket")
        for ln in lines
    )
    assert "llm_session_kv_blocks_count 1" in lines


def test_trace_json_kv_track():
    """KV-cache events (tier transitions, swap-ins, handoff
    export/import) render on their own named track, instant-linked to
    the owning request via their args; non-KV annotations stay on the
    dispatch track."""
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.request_queued(1, prompt_tokens=32)
    clk.advance(0.01)
    obs.annotate("kv_demote", block=3, depth=2)
    obs.annotate("fault", site="step")  # non-KV control
    obs.annotate("prefix_export", blocks=2, request_id="sess-1")
    obs.record_swap_in(12.5, blocks=2)  # emits kv_swap_in
    doc = obs.trace_json()
    names = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert "kv cache" in names
    kv_tid = next(
        e["tid"] for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and e["args"]["name"] == "kv cache"
    )
    inst = {
        e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "i"
    }
    for nm in ("kv_demote", "prefix_export", "kv_swap_in"):
        assert inst[nm]["tid"] == kv_tid, nm
    assert inst["fault"]["tid"] == 1  # non-KV stays on dispatches
    # The request link: args carry the emitter's request id.
    assert inst["prefix_export"]["args"]["request_id"] == "sess-1"
    # KV track never collides with a request track.
    req_tids = {
        e["tid"] for e in doc["traceEvents"]
        if e.get("cat") == "request"
    }
    assert kv_tid not in req_tids


def test_annotation_ring_bounded():
    obs = Observability(max_events=4, clock=FakeClock())
    for i in range(10):
        obs.annotate("fault_injected", site="step", kind="error", call=i)
    assert len(obs.events) == 4
    assert obs.events[-1]["fields"]["call"] == 9


def test_evict_locked_ring_pressure_no_orphans_and_decision_join():
    """SATELLITE PIN (ISSUE 15): timelines evicted under ring pressure
    — including LIVE ones in the pathological all-live branch — must
    leave no orphaned ``_by_rid`` entries, make every later touch of
    the evicted rid a clean no-op (no resurrection, no miscount), and
    never corrupt the decision join by request_id (the join degrades
    to decisions-only for an evicted timeline)."""
    obs = Observability(max_timelines=8, clock=FakeClock())
    # 16 LIVE timelines: the terminal-preference scan finds none, so
    # the oldest live ones go — the hard-bound branch.
    for rid in range(16):
        obs.request_queued(rid, prompt_tokens=4)
        obs.bind(rid, f"req-{rid}")
    assert len(obs._timelines) == 8
    # No orphans: every rid index entry points at a timeline that is
    # still reachable under its request_id.
    for rid, tl in obs._by_rid.items():
        assert obs._timelines.get(tl.request_id) is tl
    assert obs.timeline_json("req-0") is None     # evicted
    assert obs.timeline_json("req-15") is not None
    # A dispatch naming an evicted rid neither crashes nor resurrects
    # it; spans of retained timelines still link.
    obs.record_dispatch("decode", rids=[0, 15])
    assert 0 not in obs._by_rid
    tl15 = obs.timeline_json("req-15")
    assert tl15["spans"][0]["dispatches"], "live span keeps its link"
    # request_end on the evicted rid is a clean no-op — the finished
    # counter must not move for a request /debug can no longer name.
    fin0 = obs.requests_finished_total
    obs.request_end(0, "finished")
    assert obs.requests_finished_total == fin0
    # Decision join under eviction: decisions recorded for the evicted
    # id still answer by request_id (decisions-only degradation).
    obs.decisions.record("route", request_id="req-0", replica=1)
    joined = obs.decisions.for_request("req-0")
    assert len(joined) == 1 and joined[0]["replica"] == 1
    # Terminal preference: once terminal timelines exist they are
    # evicted FIRST, keeping every live (debuggable) one resident.
    obs.request_end(8, "finished")
    obs.request_end(9, "failed", "boom")
    for rid in range(16, 18):
        obs.request_queued(rid, prompt_tokens=4)
        obs.bind(rid, f"req-{rid}")
    assert "req-8" not in obs._timelines
    assert "req-9" not in obs._timelines
    for live in (10, 11, 17):
        assert f"req-{live}" in obs._timelines
    for rid, tl in obs._by_rid.items():
        assert obs._timelines.get(tl.request_id) is tl


def test_metric_snapshot_ring_bounded_and_stamped():
    obs = Observability(max_snapshots=4, clock=FakeClock())
    for i in range(10):
        obs.record_metrics_snapshot({"emitted_tokens_total": i})
    snaps = obs.metric_snapshots_json()
    assert len(snaps) == 4
    assert snaps[-1]["emitted_tokens_total"] == 9
    assert "t_ms" in snaps[-1] and "unix_s" in snaps[-1]


def test_structured_logger_tail_ring(capsys):
    log = StructuredLogger(quiet=True, ring=3)
    for i in range(5):
        log.log("event", index=i)
    assert capsys.readouterr().out == ""  # quiet: ring only
    tail = log.tail()
    assert len(tail) == 3 and tail[-1] == "event index=4"
    assert log.tail(1) == ["event index=4"]


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace_event export schema
# ---------------------------------------------------------------------------

def test_trace_json_schema():
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.request_queued(1, 4)
    obs.bind(1, "req-a")
    clk.advance(0.020)
    obs.begin_span(1, "decoding")
    obs.record_dispatch(kind="decode", k=4, occupancy=1, wall_ms=3.0,
                        rids=[1])
    obs.annotate("quarantine_transition", feature="flash_attention",
                 state="quarantined")
    clk.advance(0.010)
    obs.request_end(1, "finished")

    doc = json.loads(json.dumps(obs.trace_json()))  # JSON round-trips
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    assert doc["displayTimeUnit"] == "ms"
    for ev in evs:
        assert ev["ph"] in ("M", "X", "i")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert "name" in ev
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 1  # us, integer-safe
        if ev["ph"] == "i":
            assert ev["s"] == "g"
    # One metadata track for dispatches, one per request.
    meta = [e for e in evs if e["ph"] == "M"]
    names = {e["args"]["name"] for e in meta}
    assert "dispatches" in names and "req req-a" in names
    # Request lifecycle slices carry their dispatch links.
    req_slices = [e for e in evs if e.get("cat") == "request"]
    assert any(e["args"]["dispatches"] for e in req_slices)
    annos = [e for e in evs if e.get("cat") == "annotation"]
    assert annos and annos[0]["args"]["feature"] == "flash_attention"


def test_trace_json_window_filters_old_events():
    clk = FakeClock()
    obs = Observability(clock=clk)
    obs.record_dispatch(kind="decode", k=1, wall_ms=1.0)
    clk.advance(10.0)
    obs.record_dispatch(kind="decode", k=2, wall_ms=1.0)
    evs = obs.trace_json(window_ms=1000.0)["traceEvents"]
    dispatch = [e for e in evs if e.get("cat") == "dispatch"]
    assert len(dispatch) == 1 and dispatch[0]["args"]["seq"] == 1


# ---------------------------------------------------------------------------
# Device-time attribution: per-kind histograms, cost models, compiles
# ---------------------------------------------------------------------------

def test_per_kind_dispatch_histograms_and_utilization():
    """Dispatches split into per-kind labeled dispatch_ms series; a
    dispatch carrying a cost model feeds the per-kind utilization
    window (flops/bytes over wall vs the configured peaks) and its
    record gains a roofline device-time estimate."""
    obs = Observability(peak_flops=1e12, peak_bytes_per_s=1e12)
    # 1 GFLOP + 1 MB over 10 ms wall -> 10% MXU, ~0.01% HBM, and a
    # device estimate of 1 ms -> host_overhead_ratio 10.
    obs.record_dispatch(kind="decode", k=4, wall_ms=10.0,
                        program="_paged_decode_chunk",
                        flops=1e8, bytes_accessed=1e6)
    obs.record_dispatch(kind="spec", k=2, wall_ms=5.0)  # no model
    rec = list(obs.dispatches)[0]
    assert rec["program"] == "_paged_decode_chunk"
    assert rec["device_est_ms"] == pytest.approx(0.1)
    assert obs.hist_dispatch["decode"].count == 1
    assert obs.hist_dispatch["spec"].count == 1
    lines = obs.expose_histograms()
    # ONE family header, labeled series per kind.
    assert lines.count("# TYPE llm_dispatch_ms histogram") == 1
    assert any(
        ln.startswith('llm_dispatch_ms_bucket{kind="decode",le=')
        for ln in lines
    )
    assert 'llm_dispatch_ms_count{kind="spec"} 1' in lines
    util = {
        (fam, lab.get("kind")): v
        for fam, lab, v in obs.utilization_metrics()
    }
    assert util[("mxu_utilization", "decode")] == pytest.approx(0.01)
    assert util[("host_overhead_ratio", "decode")] == pytest.approx(
        100.0
    )
    # The model-less spec dispatch feeds no utilization window.
    assert ("mxu_utilization", "spec") not in util


def test_cost_model_cache_computes_once_and_caches_failure():
    calls = {"n": 0}

    class _Lowered:
        def cost_analysis(self):
            return {"flops": 8.0, "bytes accessed": 16.0}

    def lower():
        calls["n"] += 1
        return _Lowered()

    cache = CostModelCache()
    assert cache.get("p", (4, True), lower) == (8.0, 16.0)
    assert cache.get("p", (4, True), lower) == (8.0, 16.0)
    assert calls["n"] == 1  # trace-time only: the second get is a hit
    assert cache.get("p", (8, True), lower) == (8.0, 16.0)
    assert calls["n"] == 2  # a new jit-cache key lowers once more

    def broken():
        raise RuntimeError("exotic sharded lowering")

    assert cache.get("q", (), broken) is None
    assert cache.get("q", (), broken) is None  # failure cached too
    snap = cache.snapshot()
    assert snap["p"]["keys"] == 2 and snap["p"]["modeled"] == 2
    assert snap["q"]["modeled"] == 0


def test_compile_recording_spans_and_counters():
    """record_compile (the jax.monitoring listener's sink) feeds the
    compile_ms histogram, the per-program counters, and a span on the
    trace's dedicated 'jit compiles' track; the trace carries the
    wall-clock anchor the fleet merge normalizes with."""
    clk = FakeClock()
    obs = Observability(clock=clk)
    clk.advance(0.100)
    obs.record_compile("_fused_chunk", 40.0)
    obs.record_compile("_fused_chunk", 10.0)
    obs.record_compile("_paged_insert", 5.0)
    assert obs.hist["compile_ms"].count == 3
    assert obs.metrics()["compiles_total"] == 3
    assert obs.compiles_by_program == {
        "_fused_chunk": 2, "_paged_insert": 1,
    }
    assert (
        "program_compiles_total", {"program": "_fused_chunk"}, 2,
    ) in obs.utilization_metrics()
    doc = obs.trace_json()
    assert doc["t0_unix_s"] > 0
    compiles = [
        e for e in doc["traceEvents"] if e.get("cat") == "compile"
    ]
    assert len(compiles) == 3
    assert compiles[0]["name"] == "compile _fused_chunk"
    assert compiles[0]["tid"] == 0  # its own track
    assert compiles[0]["dur"] == 40000  # us


# ---------------------------------------------------------------------------
# Structured logging
# ---------------------------------------------------------------------------

def test_structured_logger_json_and_text(capsys):
    StructuredLogger(json_mode=True).log(
        "request_failed", "nan guard", request_id="abc", rid=3,
        skipped=None,
    )
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["event"] == "request_failed"
    assert rec["message"] == "nan guard"
    assert rec["request_id"] == "abc" and rec["rid"] == 3
    assert "skipped" not in rec and "ts" in rec
    StructuredLogger(json_mode=False).log(
        "serving", address="http://x", endpoints="a, b"
    )
    line = capsys.readouterr().out.strip()
    assert line.startswith("serving ") and "address=http://x" in line


# ---------------------------------------------------------------------------
# Integration: the real serving loop's timelines (tiny model, CPU)
# ---------------------------------------------------------------------------

def _timeline(cb, rid):
    tl = cb.obs.timeline_json(str(rid))
    assert tl is not None, f"no timeline for rid {rid}"
    return tl


def _assert_links_resolve(cb, tl):
    """Every span's dispatch links resolve to real records of the
    global ring, and each linked record lists this request's rid."""
    ring = {d["seq"]: d for d in cb.obs.dispatches_json(4096)["dispatches"]}
    rids = set(tl["rids"])
    linked = [s for sp in tl["spans"] for s in sp["dispatches"]]
    assert linked, "expected at least one dispatch link"
    for seq in linked:
        assert seq in ring, f"span links dispatch {seq} not in ring"
        assert rids & set(ring[seq]["rids"])


def test_classic_admission_span_lifecycle(model):
    """prefill_budget=0: whole-prompt insert admission.  Timeline is
    queued -> prefilling -> decoding -> finished, the prefilling span
    links the classic ``insert`` dispatch, decoding links decode
    chunks."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           decode_chunk=4, prefill_budget=0)
    rid = cb.submit([5, 6, 7, 8], max_new_tokens=8)
    cb.run_to_completion()
    tl = _timeline(cb, rid)
    assert [sp["state"] for sp in tl["spans"]] == [
        "queued", "prefilling", "decoding",
    ]
    assert tl["outcome"] == "finished"
    _assert_links_resolve(cb, tl)
    kinds = {d["kind"] for d in tl["dispatch_spans"]}
    assert "insert" in kinds and "decode" in kinds
    ins = [d for d in tl["dispatch_spans"] if d["kind"] == "insert"][0]
    assert ins["prefill_tokens"] == 4
    assert sum(
        h.count for h in cb.obs.hist_dispatch.values()
    ) >= len(tl["dispatch_spans"])


def test_fused_admission_span_lifecycle(model):
    """A warm-pool admission rides the fused prefill lane: its
    prefilling span links prefill-carrying chunk dispatches (kind
    ``fused``, prefill_tokens > 0) and decode rows kept emitting."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           decode_chunk=4, prefill_budget=32)
    cb.submit(list(np.random.RandomState(0).randint(1, 128, 9)),
              max_new_tokens=60)
    for _ in range(4):
        cb.step()  # get row 0 into steady decode
    rid = cb.submit(list(np.random.RandomState(1).randint(1, 128, 40)),
                    max_new_tokens=4)
    cb.run_to_completion()
    tl = _timeline(cb, rid)
    states = [sp["state"] for sp in tl["spans"]]
    assert states == ["queued", "prefilling", "decoding"]
    assert tl["outcome"] == "finished"
    _assert_links_resolve(cb, tl)
    pf_span = tl["spans"][1]
    fused = [
        d for d in tl["dispatch_spans"]
        if d["seq"] in pf_span["dispatches"]
    ]
    assert fused and all(d["prefill_tokens"] > 0 for d in fused)
    assert any(d["kind"] == "fused" for d in fused)
    # The fused dispatches carried decode rows too (occupancy >= 2).
    assert all(d["occupancy"] >= 2 for d in fused)


# slow (r17 budget rebalance, ~10 s): the span/dispatch-link contract
# stays tier-1-pinned by the classic and fused lifecycle drills above,
# and the spec path's observability surface stays tier-1-pinned by
# test_perf_smoke.py::test_spec_metrics_surface (gauges) and
# test_spec_steady_state_host_sync_discipline (per-dispatch counters);
# the spec span drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_spec_admission_span_lifecycle(model):
    """Speculative serving records ``spec`` dispatch spans; the
    request's decoding span links them."""
    params, config = model
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           draft_params=draft_params,
                           draft_config=draft_config,
                           n_draft=2, spec_rounds=4)
    rid = cb.submit([4, 5, 6], max_new_tokens=10)
    cb.run_to_completion()
    tl = _timeline(cb, rid)
    assert tl["outcome"] == "finished"
    assert [sp["state"] for sp in tl["spans"]] == [
        "queued", "prefilling", "decoding",
    ]
    _assert_links_resolve(cb, tl)
    dec = tl["spans"][2]
    spec = [
        d for d in tl["dispatch_spans"]
        if d["seq"] in dec["dispatches"]
    ]
    assert spec and all(d["kind"] == "spec" for d in spec)


def test_failed_request_timeline_records_error(model):
    """cancel() closes the timeline as cancelled; the non-finite path
    is covered by the faults suite — here we pin the terminal record."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    rid = cb.submit([4, 5, 6], max_new_tokens=40)
    cb.step()
    assert cb.cancel(rid)
    tl = _timeline(cb, rid)
    assert tl["outcome"] == "cancelled"
    assert tl["spans"][-1]["end_ms"] is not None
    # The server's deadline reaper passes outcome="failed" so timeouts
    # count under requests_failed_total, never as cancellations.
    rid2 = cb.submit([7, 8, 9], max_new_tokens=40)
    cb.step()
    assert cb.cancel(rid2, outcome="failed", error="generation timed out")
    tl2 = _timeline(cb, rid2)
    assert tl2["outcome"] == "failed"
    assert tl2["error"] == "generation timed out"
    assert cb.obs.requests_failed_total == 1
    assert cb.obs.requests_cancelled_total == 1


def test_restoring_fused_admission_full_timeline(model):
    """THE acceptance-criterion drill: a session whose radix prefix was
    demoted to the host tier comes back while another row decodes — it
    admits through restoring (async swap-in overlapped on the decode
    chunk) and then the FUSED prefill lane.  Its timeline holds all
    four lifecycle states, every span links real dispatch spans (the
    restoring span links the ``adopt`` scatter), the swap-in histogram
    saw the restore, and the whole window exports as Perfetto-loadable
    trace_event JSON."""
    params, config = model
    rng = np.random.RandomState(41)
    session = rng.randint(1, 128, size=40).tolist()
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=128, block_size=BS,
        n_blocks=8, prefix_cache=True, host_kv_blocks=4,
        decode_chunk=4, prefill_budget=32,
    )
    # Seed the session chain (2 keyed blocks), then demote it to the
    # host tier explicitly.
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    assert cb.demote_idle(2) == 2
    assert cb.stats()["host_tier_blocks"] == 2
    # A long-running decode occupies a row, so the session's revisit
    # must overlap its swap-in with live decode chunks and admit fused.
    # Geometry: the filler reserves 4 of 8 blocks (9+40 -> 64 padded),
    # leaving 4 free — enough for the 2-block restore staging plus the
    # session's suffix, so the swap really does fly WHILE the filler
    # decodes (a bigger filler would starve the restore of fresh
    # blocks and the session would fall back to a cold-pool suffix
    # admission after the filler finished).
    cb.submit(rng.randint(1, 128, size=9).tolist(), max_new_tokens=40)
    for _ in range(4):
        cb.step()
    cb.swap_poll_min = 2  # hold the restore window open >= 2 polls
    rid = cb.submit(list(session), max_new_tokens=4)
    saw_restoring = False
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 300
        cb.step()
        saw_restoring = saw_restoring or bool(cb._restoring)
    assert saw_restoring
    st = cb.stats()
    assert st["swap_ins_total"] == 1

    tl = _timeline(cb, rid)
    assert tl["outcome"] == "finished"
    states = [sp["state"] for sp in tl["spans"]]
    # queued -> restoring -> queued(restored) -> prefilling -> decoding
    assert set(states) >= {"queued", "restoring", "prefilling",
                           "decoding"}
    assert states[0] == "queued" and states[1] == "restoring"
    assert states[-1] == "decoding"
    restored = tl["spans"][2]
    assert restored["state"] == "queued" and restored["note"] == "restored"
    _assert_links_resolve(cb, tl)
    # The restoring span links the adoption scatter dispatch.
    rest_span = tl["spans"][1]
    adopt = [
        d for d in tl["dispatch_spans"]
        if d["seq"] in rest_span["dispatches"]
    ]
    assert adopt and adopt[-1]["kind"] == "adopt"
    # The fused prefill rode dispatches that also carried the decode row.
    pf_span = tl["spans"][states.index("prefilling")]
    carried = [
        d for d in tl["dispatch_spans"]
        if d["seq"] in pf_span["dispatches"]
    ]
    assert carried and all(d["occupancy"] >= 2 for d in carried)
    # Swap-in latency landed in its histogram + the annotation ring.
    assert cb.obs.hist["swap_in_ms"].count == 1
    assert any(e["name"] == "kv_swap_in" for e in cb.obs.events)
    assert any(e["name"] == "kv_demote" for e in cb.obs.events)
    # The serving window exports as valid trace_event JSON.
    doc = json.loads(json.dumps(cb.obs.trace_json()))
    evs = doc["traceEvents"]
    assert any(
        e.get("cat") == "request" and e["name"] == "restoring"
        for e in evs
    )
    assert any(
        e.get("cat") == "dispatch" and e["name"].startswith("adopt")
        for e in evs
    )


def test_obs_survives_rebuild_one_continuous_trace(model):
    """rebuild() (the crash-recovery primitive) reuses the SAME
    Observability via the captured ctor kwargs: timelines and dispatch
    seqs continue instead of resetting."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    cb.submit([4, 5, 6], max_new_tokens=4)
    cb.run_to_completion()
    seq_before = cb.obs._seq
    cb2 = cb.rebuild()
    assert cb2.obs is cb.obs
    rid = cb2.submit([7, 8, 9], max_new_tokens=4)
    cb2.run_to_completion()
    tl = cb2.obs.timeline_json(str(rid))
    assert tl["outcome"] == "finished"
    assert min(
        s for sp in tl["spans"] for s in sp["dispatches"]
    ) >= seq_before


def test_fault_injection_annotated_in_trace(model):
    """An injected fault lands as an instant event in the annotation
    ring (the batcher wires injector.trace_sink at construction), so a
    chaos drill's fault is explainable next to the dispatch spans it
    killed."""
    from jax_llama_tpu.faults import FaultInjector, InjectedFault

    params, config = model
    inj = FaultInjector("step@1:error")
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           fault_injector=inj)
    cb.submit([4, 5, 6], max_new_tokens=8)
    with pytest.raises(InjectedFault):
        for _ in range(8):
            cb.step()
    faults = [e for e in cb.obs.events if e["name"] == "fault_injected"]
    assert faults and faults[0]["fields"]["site"] == "step"
    assert faults[0]["fields"]["kind"] == "error"
