"""Kernel-selection layer (ops/kernels.py): the pluggable splash-mha
prefill and stock Pallas paged-attention decode kernels.

What is pinned here, all CPU-runnable via Pallas ``interpret=True``:

  * registry/resolution: the auto policies, the unknown-name errors,
    the per-chunk splash eligibility predicate, and that every
    selectable kernel's fallback ladder / degrade feature / fault site
    actually exist in degrade.py, faults.py and obs.py — the PR-11/12
    landing-checklist wiring, checked as data;
  * op-level numerics: splash prefill vs a dense causal reference
    (offset mask, GQA head mapping) and the stock decode kernel vs an
    explicit bf16-cast gathered reference (TIGHT — that is the kernel's
    documented arithmetic) and vs the custom paged kernel (LOOSE — the
    stock kernel casts K/V tiles to bf16 in-kernel, a documented ~3e-3
    divergence on fp32 pools, which is why stock-vs-custom greedy
    serving is A/B-comparable but not token-identical);
  * serving-level behavior: a splash batcher is TOKEN-IDENTICAL to the
    flash batcher (same fp32 math, different pipelining), the stock
    decode path is chunking-invariant (K=1 vs K=4 token-identical),
    the speculative path with a stock-paged draft is token-identical
    to the plain custom batcher (the target's verify sweep stays on
    the custom kernel), and each kernel books its own dispatch kind
    ("insert:splash" / "decode:stock-paged") for per-kernel MXU
    attribution;
  * quarantine drills: every splash/stock dispatch faulting quarantines
    the kernel's OWN feature and the batcher rebuilds onto the EXISTING
    custom kernel — mid-stream, with delivered tokens identical to the
    fallback-kernel healthy reference (faults fire before dispatch, so
    no divergent token is ever emitted, and the replay is
    teacher-forced).

TPU companions (compiled Mosaic vs the interpret path) ride the ``tpu``
marker and self-skip off-chip; they are also marked ``slow`` so tier-1
collection never pays for them.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.degrade import FEATURES
from jax_llama_tpu.faults import SITES, FaultInjector
from jax_llama_tpu.obs import DISPATCH_KINDS
from jax_llama_tpu.ops.kernels import (
    DECODE_KERNELS,
    PREFILL_KERNELS,
    resolve_decode_kernel,
    resolve_prefill_kernel,
    splash_eligible,
    splash_prefill,
    stock_paged_decode,
)
from jax_llama_tpu.ops.paged_attention import paged_decode_attention
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

requires_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="needs the real TPU chip (run: pytest -m tpu)",
)

# The stock kernel's tiny serving geometry (d=16 — identical to
# test_degrade's): the stock decode path has no lane-alignment
# requirement in interpret mode.  The SPLASH geometry needs head_dim
# 128 (the kernel's lane tiling), so it gets its own config; with
# block_size=128 every cold insert pads to a 128-multiple P and the
# whole-prompt chunk is splash-eligible.
CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32",
    param_dtype="float32",
)
SPLASH_CFG = dict(
    vocab_size=128, dim=256, n_layers=2, n_heads=2, n_kv_heads=1,
    multiple_of=32, max_seq_len=256, dtype="float32",
    param_dtype="float32", attn_impl="auto",
)
PROMPTS = [[5, 17, 99, 3], [7, 8, 9]]
MAX_NEW = 6


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


@pytest.fixture(scope="module")
def reference(model):
    """Healthy greedy tokens through the CUSTOM paged kernel — the
    oracle for the stock-paged fallback/identity assertions."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


@pytest.fixture(scope="module")
def splash_model():
    config = get_config("tiny", **SPLASH_CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


@pytest.fixture(scope="module")
def flash_reference(splash_model):
    """Healthy greedy tokens through the CUSTOM flash prefill on the
    splash-eligible config — the oracle splash must match exactly."""
    params, config = splash_model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=256, block_size=128,
        prefill_kernel="flash",
    )
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _health(url):
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=60) as r:
            body = r.read().decode()
    except urllib.error.HTTPError as e:
        body = e.read().decode()
    return json.loads(body)


def _kinds(cb):
    return {d["kind"] for d in cb.obs.dispatches_json()["dispatches"]}


# ---------------------------------------------------------------------------
# Registry / resolution (pure host — no jax arrays)
# ---------------------------------------------------------------------------

def test_resolution_auto_policies(model, splash_model):
    _, small = model          # head_dim 16: splash structurally out
    _, wide = splash_model    # head_dim 128: splash-capable
    assert resolve_prefill_kernel("auto", small) == "flash"
    assert resolve_prefill_kernel(None, small) == "flash"
    assert resolve_prefill_kernel("auto", wide) == "splash"
    # int8 pools stay on the custom kernels under auto.
    assert resolve_prefill_kernel(
        "auto", wide.replace(kv_cache_dtype="int8")
    ) == "flash"
    # Decode auto keeps the custom kernel (int8, multi-token verify,
    # measured grid); stock stays the explicit A/B choice.
    assert resolve_decode_kernel("auto", small) == "paged"
    assert resolve_decode_kernel(None, wide) == "paged"
    assert resolve_decode_kernel("stock-paged", small) == "stock-paged"
    with pytest.raises(ValueError, match="unknown prefill kernel"):
        resolve_prefill_kernel("nosuch", small)
    with pytest.raises(ValueError, match="unknown decode kernel"):
        resolve_decode_kernel("nosuch", small)


def test_splash_eligibility_gates(splash_model):
    _, cfg = splash_model
    cfg = cfg.replace(prefill_kernel="splash")
    ok = dict(batch=2, q_len=128, kv_len=256, chunk_offset=0,
              quantized=False, mesh=None)
    assert splash_eligible(cfg, **ok)
    # Each structural requirement gates independently.
    assert not splash_eligible(cfg, **{**ok, "q_len": 120})
    assert not splash_eligible(cfg, **{**ok, "kv_len": 130})
    assert not splash_eligible(cfg, **{**ok, "chunk_offset": None})
    assert not splash_eligible(cfg, **{**ok, "quantized": True})
    assert not splash_eligible(
        cfg.replace(prefill_kernel="flash"), **ok
    )


def test_registry_wiring_is_complete():
    """The landing checklist as data: every selectable kernel's
    fallback names a registered kernel of the same role, and its
    degrade feature / fault site / dispatch kind all exist where
    serving will look them up."""
    assert PREFILL_KERNELS["splash"].fallback == "flash"
    assert DECODE_KERNELS["stock-paged"].fallback == "paged"
    for reg in (PREFILL_KERNELS, DECODE_KERNELS):
        for spec in reg.values():
            if spec.fallback is not None:
                assert spec.fallback in reg
            if spec.feature is not None:
                assert spec.feature in FEATURES
            if spec.fault_site is not None:
                assert spec.fault_site in SITES
    # Per-kernel MXU attribution kinds (obs.py validates these).
    assert "insert:splash" in DISPATCH_KINDS
    assert "decode:stock-paged" in DISPATCH_KINDS


# ---------------------------------------------------------------------------
# Op-level parity (Pallas interpret mode)
# ---------------------------------------------------------------------------

def _pool_state(rng, B, KVH, d, L, NB, BLK, MB, fills):
    """A multi-layer block pool with per-row fills: returns the 5-D
    k/v pools, the slot-position map, and the block table (same layout
    test_paged_attention pins for the custom kernel)."""
    kp = rng.randn(L, KVH, NB, BLK, d).astype(np.float32)
    vp = rng.randn(L, KVH, NB, BLK, d).astype(np.float32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    table = np.full((B, MB), NB, np.int32)
    free = list(range(NB))
    for b, fill in enumerate(fills):
        n = -(-fill // BLK) if fill else 0
        blocks = [free.pop(0) for _ in range(n)]
        table[b, :n] = blocks
        for j, blk in enumerate(blocks):
            m = min(BLK, fill - j * BLK)
            pool_pos[blk, :m] = np.arange(j * BLK, j * BLK + m)
    return kp, vp, pool_pos, table


def _stock_case(seed=0):
    rng = np.random.RandomState(seed)
    B, H, KVH, d = 4, 8, 2, 32
    L, NB, BLK, MB = 2, 12, 16, 5
    # multi-block, empty (inactive), one block, partial block
    fills = [40, 0, 16, 7]
    qpos = np.array([40, -1, 16, 7], np.int32)
    kp, vp, pool_pos, table = _pool_state(
        rng, B, KVH, d, L, NB, BLK, MB, fills
    )
    q = rng.randn(B, 1, H, d).astype(np.float32)
    kn = rng.randn(B, 1, KVH, d).astype(np.float32)
    vn = rng.randn(B, 1, KVH, d).astype(np.float32)
    return q, kn, vn, kp, vp, pool_pos, table, qpos


def _bf16_reference(q, kn, vn, kp, vp, table, qpos, layer, b):
    """Row b's attention with pool K/V cast to bf16 BEFORE the math —
    exactly the stock kernel's documented in-kernel cast; the step's
    own slot merges at fp32 (outside the kernel)."""
    _, _, H, d = q.shape
    KVH, NB = kp.shape[1], kp.shape[2]
    G = H // KVH
    scale = 1.0 / np.sqrt(d)
    f = int(qpos[b])
    ks = [kp[layer][:, t] for t in table[b] if t < NB]
    vs = [vp[layer][:, t] for t in table[b] if t < NB]
    kcat = np.concatenate(ks, axis=1)[:, :f]    # [KVH, f, d]
    vcat = np.concatenate(vs, axis=1)[:, :f]
    kb = np.asarray(
        jnp.asarray(kcat).astype(jnp.bfloat16).astype(jnp.float32)
    )
    vb = np.asarray(
        jnp.asarray(vcat).astype(jnp.bfloat16).astype(jnp.float32)
    )
    out = np.zeros((H, d), np.float32)
    for h in range(H):
        kh = h // G
        s = np.concatenate([
            (q[b, 0, h] * scale) @ kb[kh].T,
            [(q[b, 0, h] @ kn[b, 0, kh]) * scale],
        ])
        w = np.exp(s - s.max())
        w /= w.sum()
        out[h] = w[:-1] @ vb[kh] + w[-1] * vn[b, 0, kh]
    return out


def test_stock_decode_matches_bf16_reference():
    """TIGHT parity vs the explicit bf16-cast gathered reference: the
    flat-page layer/head offsets, the lse merge of the step's own
    slot, and the GQA head grouping are exact; inactive rows (q_pos
    -1) produce finite discarded output."""
    q, kn, vn, kp, vp, _, table, qpos = _stock_case()
    layer = 1
    got = np.asarray(stock_paged_decode(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(table),
        jnp.asarray(qpos), jnp.asarray(layer, jnp.int32), interpret=True,
    ))
    assert np.isfinite(got).all()
    for b in range(q.shape[0]):
        if qpos[b] < 0:
            continue
        want = _bf16_reference(q, kn, vn, kp, vp, table, qpos, layer, b)
        np.testing.assert_allclose(got[b, 0], want, atol=1e-5, rtol=1e-5)


def test_stock_decode_tracks_custom_kernel_loosely():
    """LOOSE parity vs the custom paged kernel: same contract, but the
    stock kernel's in-kernel bf16 K/V cast rounds fp32 pools once more
    (~3e-3 here) — the reason stock-vs-custom serving is A/B-compared,
    never asserted token-identical."""
    q, kn, vn, kp, vp, pool_pos, table, qpos = _stock_case()
    layer = 1
    got = np.asarray(stock_paged_decode(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(table),
        jnp.asarray(qpos), jnp.asarray(layer, jnp.int32), interpret=True,
    ))
    custom = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp[layer]), jnp.asarray(vp[layer]),
        jnp.asarray(pool_pos), jnp.asarray(table), jnp.asarray(qpos),
    ))
    for b in range(q.shape[0]):
        if qpos[b] < 0:
            continue
        np.testing.assert_allclose(
            got[b], custom[b], atol=2e-2, rtol=2e-2
        )


def test_stock_decode_layer_select_and_guards():
    """The flat-page offset must pick exactly the (layer, head) plane a
    4-D single-layer launch of that plane picks; the T > 1 and
    missing-layer misuses raise before any launch."""
    q, kn, vn, kp, vp, _, table, qpos = _stock_case(seed=3)
    five_d = np.asarray(stock_paged_decode(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(table),
        jnp.asarray(qpos), jnp.asarray(1, jnp.int32), interpret=True,
    ))
    four_d = np.asarray(stock_paged_decode(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp[1]), jnp.asarray(vp[1]), jnp.asarray(table),
        jnp.asarray(qpos), interpret=True,
    ))
    np.testing.assert_array_equal(five_d, four_d)
    with pytest.raises(ValueError, match="multi-layer pool"):
        stock_paged_decode(
            jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
            jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(table),
            jnp.asarray(qpos), interpret=True,
        )
    with pytest.raises(NotImplementedError, match="T == 1 only"):
        stock_paged_decode(
            jnp.asarray(np.repeat(q, 2, axis=1)), jnp.asarray(kn),
            jnp.asarray(vn), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(qpos),
            jnp.asarray(1, jnp.int32), interpret=True,
        )


def test_splash_prefill_matches_dense_reference():
    """Splash vs dense causal attention at a chunk offset: query row t
    at absolute position offset+t attends cache columns j <= offset+t,
    GQA query head h reads KV head h // group, and the caller-side
    d**-0.25 double-scaling reproduces plain 1/sqrt(d) softmax."""
    B, T, S, H, KVH, d = 2, 128, 256, 4, 2, 128
    off = 128
    rng = np.random.RandomState(1)
    q = rng.randn(B, T, H, d).astype(np.float32) * 0.5
    k = rng.randn(B, S, KVH, d).astype(np.float32) * 0.5
    v = rng.randn(B, S, KVH, d).astype(np.float32) * 0.5
    got = np.asarray(splash_prefill(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        chunk_offset=off, interpret=True,
    ))
    G = H // KVH
    scale = d ** -0.5
    mask = np.arange(S)[None, :] <= (np.arange(T)[:, None] + off)
    for b in range(B):
        for h in range(H):
            s = (q[b, :, h] @ k[b, :, h // G].T) * scale
            s = np.where(mask, s, -1e30)
            w = np.exp(s - s.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            np.testing.assert_allclose(
                got[b, :, h], w @ v[b, :, h // G], atol=1e-5, rtol=1e-5
            )


# ---------------------------------------------------------------------------
# Serving-level behavior (CPU, interpret-mode kernels)
# ---------------------------------------------------------------------------

def test_serving_splash_token_identical_to_flash(
    splash_model, flash_reference
):
    """The splash batcher's greedy tokens match the flash batcher's
    EXACTLY (both fp32 — the kernels differ in pipelining, not math),
    and the insert books its per-kernel dispatch kind."""
    params, config = splash_model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=256, block_size=128,
        prefill_kernel="splash",
    )
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    assert [out[r] for r in rids] == flash_reference
    assert "insert:splash" in _kinds(cb)


# slow (r17 budget rebalance, ~8 s): the stock kernel's numerics stay
# tier-1-pinned op-level (the bf16-reference and loose-custom parity
# cells above) and its serving fallback stays tier-1-pinned by the
# quarantine drill below; the K=1-vs-K=4 serving drain rides the slow
# tier (`make kernels` and the unfiltered suite still run it).
@pytest.mark.slow
def test_serving_stock_decode_chunking_invariant(model):
    """The stock decode path must be chunking-invariant: K=1 and K=4
    drains are token-identical (the kernel sees identical per-step
    geometry either way), and pure-decode chunks book the
    "decode:stock-paged" attribution kind."""
    params, config = model

    def run(K):
        cb = ContinuousBatcher(
            params, config, n_slots=2, max_len=64,
            decode_kernel="stock-paged", decode_chunk=K,
        )
        rids = [
            cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS
        ]
        out = cb.run_to_completion()
        return [out[r] for r in rids], _kinds(cb)

    toks1, kinds1 = run(1)
    toks4, kinds4 = run(4)
    assert toks1 == toks4
    assert "decode:stock-paged" in kinds1
    assert "decode:stock-paged" in kinds4


# slow (r17 budget rebalance, ~6 s): the two composing contracts keep
# tier-1 pins — stock decode numerics op-level above, speculative
# serving identity in tests/test_serving_spec.py — so the composed
# stock-draft drill rides slow (`make kernels` still runs it).
@pytest.mark.slow
def test_serving_spec_stock_draft_token_identity(model, reference):
    """Speculative serving with a stock-paged DRAFT stays
    token-identical to the plain custom batcher: the target's verify
    sweep keeps the custom kernel (T = G+1 > 1), so acceptance
    decisions — and therefore emitted tokens — never see the stock
    kernel's bf16 rounding."""
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        decode_kernel="stock-paged",
        draft_params=params, draft_config=config, n_draft=2,
    )
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    assert [out[r] for r in rids] == reference


# ---------------------------------------------------------------------------
# Quarantine drills: each opt-in kernel falls back to the EXISTING
# custom kernel, token-identically, mid-stream
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_stock_paged_quarantine_falls_back_to_custom(model, reference):
    """Every stock-paged decode dispatch faults (host-side, BEFORE the
    kernel runs — no divergent token is ever delivered): the
    stock_paged feature quarantines mid-request, the batcher rebuilds
    onto the CUSTOM paged kernel (one rung, not XLA), and the delivered
    tokens are identical to the custom-kernel healthy reference."""
    params, config = model
    inj = FaultInjector("stock_paged_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        decode_kernel="stock-paged", fault_injector=inj,
    )
    results = {}
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=3600.0
    ) as srv:
        def call(i):
            try:
                _, body = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )
                results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        for i in range(len(PROMPTS)):
            assert results[i] == reference[i], i
        h = _health(srv.address)
        assert h["ok"] is True and h["degraded"] is True
        assert h["quarantined"] == ["stock_paged"]
        # One rung down the ladder: the rebuilt batcher runs the CUSTOM
        # paged kernel, not the gathered view.
        assert srv.batcher.config.decode_kernel == "paged"
        assert srv.batcher.use_pallas_kernel


@pytest.mark.faults
def test_splash_quarantine_falls_back_to_flash(
    splash_model, flash_reference
):
    """Every splash insert dispatch faults: splash_prefill quarantines,
    the batcher rebuilds with prefill_kernel='flash' (flash_attention
    itself stays healthy — its own site did not fault), and the request
    completes token-identical to the flash reference."""
    params, config = splash_model
    inj = FaultInjector("splash_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=256, block_size=128,
        prefill_kernel="splash", fault_injector=inj,
    )
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=3600.0
    ) as srv:
        _, body = _post(
            srv.address, {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == flash_reference[0]
        h = _health(srv.address)
        assert h["quarantined"] == ["splash_prefill"]
        assert srv.batcher.config.prefill_kernel == "flash"
        # The flash feature itself is untouched: one rung at a time.
        assert h["features"]["flash_attention"]["state"] == "healthy"
        # A follow-up request serves entirely on the flash path.
        _, body = _post(
            srv.address, {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == flash_reference[1]


# ---------------------------------------------------------------------------
# TPU companions (compiled Mosaic; self-skip off-chip, slow-marked so
# tier-1 never collects their cost)
# ---------------------------------------------------------------------------

@pytest.mark.tpu
@pytest.mark.slow
@requires_tpu
def test_tpu_splash_prefill_compiled_matches_dense():
    B, T, S, H, KVH, d = 1, 128, 256, 4, 2, 128
    rng = np.random.RandomState(7)
    q = rng.randn(B, T, H, d).astype(np.float32) * 0.5
    k = rng.randn(B, S, KVH, d).astype(np.float32) * 0.5
    v = rng.randn(B, S, KVH, d).astype(np.float32) * 0.5
    got = np.asarray(splash_prefill(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        chunk_offset=128, interpret=False,
    ))
    G, scale = H // KVH, d ** -0.5
    mask = np.arange(S)[None, :] <= (np.arange(T)[:, None] + 128)
    for h in range(H):
        s = (q[0, :, h] @ k[0, :, h // G].T) * scale
        s = np.where(mask, s, -1e30)
        w = np.exp(s - s.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        np.testing.assert_allclose(
            got[0, :, h], w @ v[0, :, h // G], atol=2e-2, rtol=2e-2
        )


@pytest.mark.tpu
@pytest.mark.slow
@requires_tpu
def test_tpu_stock_decode_compiled_tracks_custom():
    q, kn, vn, kp, vp, pool_pos, table, qpos = _stock_case(seed=11)
    got = np.asarray(stock_paged_decode(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(table),
        jnp.asarray(qpos), jnp.asarray(1, jnp.int32), interpret=False,
    ))
    custom = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp[1]), jnp.asarray(vp[1]), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    for b in range(q.shape[0]):
        if qpos[b] < 0:
            continue
        np.testing.assert_allclose(
            got[b], custom[b], atol=2e-2, rtol=2e-2
        )
