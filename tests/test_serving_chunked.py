"""Chunked decode (decode_chunk > 1) must be TOKEN-IDENTICAL to the
K=1 per-token loop — which existing tests pin against standalone
``engine.generate`` — across greedy and seeded-sampled policies, stop
tokens and max_new landing mid-chunk, logprobs on/off, the int8-KV
pool, and the gathered-view fallback; and the crash-recovery /
non-finite-guard / quarantine semantics proven for K=1 must hold with
chunking enabled (fault sites fire per chunk dispatch, replay works
from delivered tokens, NaN isolation stays per-request)."""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.faults import FaultInjector
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _drain(cb, want_lp=False):
    """Run to completion collecting per-request tokens (and logprobs)."""
    toks, lps = {}, {}
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 500
        for ev in cb.step():
            toks.setdefault(ev[0], []).append(ev[1])
            if want_lp:
                lps.setdefault(ev[0], []).append(ev[3])
    return toks, lps


def _run_matrix(params, config, K, *, logprobs=False, stop=(), **cb_kw):
    """The shared request mix: greedy finishing mid-chunk (max_new 5),
    greedy full-budget, and two seeded sampled policies — 4 requests
    over 2 slots, so the chunk size also ramps around queue-driven
    admissions."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, size=n).tolist() for n in (5, 9, 14, 6)]
    policies = [
        dict(max_new_tokens=5),
        dict(max_new_tokens=11),
        dict(max_new_tokens=9, temperature=0.9, seed=11),
        dict(max_new_tokens=12, temperature=0.7, top_p=0.8, seed=12),
    ]
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=K,
        logprobs=logprobs, stop_tokens=stop, **cb_kw,
    )
    rids = [cb.submit(p, **pol) for p, pol in zip(prompts, policies)]
    toks, lps = _drain(cb, want_lp=logprobs)
    return (
        [toks[r] for r in rids],
        [lps.get(r) for r in rids],
    )


# K=8 cells ride slow (r17 budget rebalance, ~5 s each): the K=4 cells
# pin chunked identity against the K=1 loop, and K-range adaptivity
# (ramp to the configured chunk) is tier-1-pinned by
# test_perf_smoke.py::test_chunk_size_adapts_around_admissions; the
# K=8 re-proof runs in the unfiltered suite.
@pytest.mark.parametrize("K", [4, pytest.param(8, marks=pytest.mark.slow)])
def test_chunk_token_identity_greedy_and_sampled(model, K):
    """K ∈ {4, 8} × {greedy, sampled} × max_new mid-chunk: identical to
    the K=1 loop (which test_serving.py pins against engine.generate)."""
    params, config = model
    base, _ = _run_matrix(params, config, 1)
    got, _ = _run_matrix(params, config, K)
    assert got == base


# K=8 rides slow with the same r17 justification as above.
@pytest.mark.parametrize("K", [4, pytest.param(8, marks=pytest.mark.slow)])
def test_chunk_token_identity_stop_token_mid_chunk(model, K):
    """A stop token landing mid-chunk ends the request at exactly that
    token: the on-device stop set must agree with the host's."""
    params, config = model
    prompt = [5, 17, 99, 3, 42]

    def run(K, stop=()):
        cb = ContinuousBatcher(
            params, config, n_slots=1, max_len=64, decode_chunk=K,
            stop_tokens=stop,
        )
        rid = cb.submit(prompt, max_new_tokens=16)
        return cb.run_to_completion()[rid]

    free = run(1)
    j = next(
        i for i in range(1, len(free)) if free[i] not in free[:i]
    )
    stop = free[j]
    want = run(1, stop=(stop,))
    got = run(K, stop=(stop,))
    assert want == free[:j + 1]
    assert got == want


@pytest.mark.slow
def test_chunk_token_identity_logprobs(model):
    """logprobs mode: the packed (bitcast) per-token logprob block must
    deliver the same values the K=1 loop reports, token for token.

    Slow tier (r14 budget rebalance, ~11 s of logprobs-program
    compiles): chunked logprob identity stays tier-1-pinned by
    test_serving_fused's identity cells, which assert the same packed
    logprob block allclose against the classic oracle on every
    tier-1 run."""
    params, config = model
    base, base_lp = _run_matrix(params, config, 1, logprobs=True)
    got, got_lp = _run_matrix(params, config, 4, logprobs=True)
    assert got == base
    for a, b in zip(got_lp, base_lp):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_chunk_token_identity_int8_kv(model):
    """The int8 pool's quantized scan branches (per-iteration scale
    plane writes inside the chunk) must match their K=1 emissions.

    Slow tier (r14 budget rebalance, ~13 s: the int8 config compiles
    its own oracle AND chunk executables): int8-KV identity stays
    tier-1-pinned by test_kvcache's int8 chunk-matched-oracle parity
    cells and test_serving_spec's int8 cell."""
    params, config = model
    import dataclasses
    qconfig = dataclasses.replace(config, kv_cache_dtype="int8")
    base, _ = _run_matrix(params, qconfig, 1, block_size=16)
    got, _ = _run_matrix(params, qconfig, 4, block_size=16)
    assert got == base


@pytest.mark.slow
def test_chunk_token_identity_gathered_fallback(model):
    """slow (r14 budget rebalance, ~7 s): the quarantine drill
    test_chunked_paged_kernel_quarantine_falls_back keeps the
    gathered-fallback-under-chunking contract in tier-1 (it lands on
    exactly this configuration and checks token identity through it).

    The gathered-view fallback (use_pallas_kernel=False) chunks
    identically — the scan body's gather/scatter path is per-iteration
    the same program as one K=1 dispatch."""
    params, config = model
    base, _ = _run_matrix(params, config, 1, use_pallas_kernel=False)
    got, _ = _run_matrix(params, config, 4, use_pallas_kernel=False)
    assert got == base


# ---------------------------------------------------------------------------
# Fault-tolerance semantics with chunking enabled
# ---------------------------------------------------------------------------

PROMPTS = [[5, 17, 99, 3], [7, 8, 9], [11, 12, 13]]
MAX_NEW = 12


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _stream_lines(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        return [json.loads(line) for line in r.read().splitlines()]


@pytest.fixture(scope="module")
def reference(model):
    """Fault-free K=1 greedy outputs (the identity oracle)."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


@pytest.mark.faults
def test_chunked_step_fault_recovers_token_exact(model, reference):
    """A step fault mid-chunked-decode (the 'step' site fires once per
    CHUNK dispatch): recovery rebuilds a chunked batcher and replays
    from delivered tokens — greedy outputs identical to the fault-free
    K=1 run, streaming clients see each token exactly once even though
    tokens now arrive in chunk-sized bursts."""
    params, config = model
    inj = FaultInjector("step@2:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        fault_injector=inj,
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                if i == 0:  # one streaming client
                    results[i] = _stream_lines(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW,
                         "stream": True},
                    )
                else:
                    _, body = _post(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                    )
                    results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001 — fail the test, not the thread
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        lines = results[0]
        assert isinstance(lines, list), lines
        streamed = [ln["token"] for ln in lines[:-1]]
        assert streamed == reference[0]          # no dup, no gap
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == reference[0]
        for i in range(1, len(PROMPTS)):
            assert results[i] == reference[i], i
        assert inj.injected_total == 1
        assert srv.recoveries_total == 1


@pytest.mark.faults
def test_chunked_nan_isolation_per_request(model, reference):
    """An armed nan poison under chunking fails exactly one request
    with a clean 500 (its chunk tokens are discarded, never streamed);
    the neighbor slot completes token-identically."""
    params, config = model
    inj = FaultInjector("step@2:nan")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        fault_injector=inj,
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                results[i] = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )[1]["tokens"]
            except urllib.error.HTTPError as e:
                results[i] = (e.code, json.loads(e.read())["error"])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
    failed = [r for r in results.values() if isinstance(r, tuple)]
    ok = {i: r for i, r in results.items() if isinstance(r, list)}
    assert len(failed) == 1
    code, msg = failed[0]
    assert code == 500 and "non-finite" in msg
    assert len(ok) == 1
    (i, toks), = ok.items()
    assert toks == reference[i]
    assert inj.nans_armed_total == 1


@pytest.mark.faults
def test_chunked_paged_kernel_quarantine_falls_back(model, reference):
    """paged_kernel faults fire once per CHUNK dispatch and quarantine
    attribution still works: past the threshold the batcher rebuilds
    onto the gathered-view fallback WITH chunking preserved, requests
    replay token-identically, and the server reports degraded-but-ok."""
    params, config = model
    inj = FaultInjector("paged_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, decode_chunk=4,
        fault_injector=inj,
    )
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=600.0
    ) as srv:
        _, body = _post(
            srv.address,
            {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW},
        )
        assert body["tokens"] == reference[0]
        assert srv.degrade.quarantined() == ("paged_kernel",)
        # The fallback batcher keeps the chunk configuration.
        assert srv.batcher.decode_chunk == 4
        assert srv.batcher.use_pallas_kernel is False
        # And keeps serving: a second request completes on the fallback.
        _, body2 = _post(
            srv.address,
            {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW},
        )
        assert body2["tokens"] == reference[1]
