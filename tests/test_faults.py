"""Fault tolerance: deterministic injection, crash recovery with request
replay, the recovery circuit breaker, and the step watchdog.

The invariants pinned here:
  * a fault injected at a mid-decode step (or at admission / allocation /
    suffix-insert) recovers: EVERY in-flight and queued request still
    completes, and greedy outputs are token-identical to a fault-free
    run — streaming clients receive no duplicated tokens;
  * exceeding the recovery budget drains cleanly: all clients get 503,
    no handler thread hangs, and /healthz reports the dead loop;
  * the watchdog flips /healthz to a degraded payload (last-step age,
    recovery count) while a step stalls, and clears it afterwards;
  * /metrics exposes recovery / injection / watchdog counters.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.faults import (
    FaultInjector,
    FaultSpec,
    InjectedFault,
    InjectedOOM,
)
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

pytestmark = pytest.mark.faults

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)

PROMPTS = [[5, 17, 99, 3], [7, 8, 9], [11, 12, 13], [2, 3, 4]]
MAX_NEW = 8


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


@pytest.fixture(scope="module")
def reference(model):
    """Fault-free greedy outputs for PROMPTS (the identity oracle)."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode()


def _stream_lines(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.status == 200
        return [json.loads(line) for line in r.read().splitlines()]


# ---------------------------------------------------------------------------
# FaultInjector unit behavior (no jax involved)
# ---------------------------------------------------------------------------

# slow (r06 budget rebalance, ~12 s): still in `make faults` / `make
# chaos` (those targets select by marker, not by 'not slow').
@pytest.mark.slow
def test_step_fault_mid_prefill_chunk_replays_exactly(model):
    """A fault landing MID-PREFILL-CHUNK (the ``prefill_chunk`` site
    indexes prefill-carrying dispatches, so ``@1`` deterministically
    kills the second chunk of the fused admission) recovers: the
    partially-prefilled request replays token-exact from its prompt,
    the streaming resident sees every token exactly once, and the
    rebuilt batcher keeps the fused-scheduling configuration."""
    params, config = model
    long_prompt = np.random.RandomState(3).randint(1, 128, 40).tolist()
    cb0 = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16,
    )
    ra = cb0.submit(list(PROMPTS[0]), max_new_tokens=24)
    rb = cb0.submit(list(long_prompt), max_new_tokens=MAX_NEW)
    out0 = cb0.run_to_completion()
    want_a, want_b = out0[ra], out0[rb]

    inj = FaultInjector("prefill_chunk@1:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16,
        decode_chunk=4, prefill_budget=16, fault_injector=inj,
    )
    with LLMServer(cb) as srv:
        # Resident streamer holds a decoding row; reading its first
        # token guarantees the pool is warm before the long prompt
        # posts — so the admission rides the FUSED path (40 suffix
        # tokens at a 16-token budget = 3 prefill-carrying dispatches;
        # the injected fault kills the second, mid-prefill).
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps({
                "prompt": PROMPTS[0], "max_new_tokens": 24,
                "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            first = json.loads(resp.readline())
            assert "token" in first
            _, body = _post(
                srv.address,
                {"prompt": long_prompt, "max_new_tokens": MAX_NEW},
            )
            lines = [first] + [
                json.loads(ln) for ln in resp.read().splitlines()
            ]
        assert inj.injected_total == 1
        assert srv.recoveries_total == 1
        # The mid-prefill request replayed token-exact...
        assert body["tokens"] == want_b
        # ...and the streaming resident saw no duplicate or gap.
        streamed = [ln["token"] for ln in lines[:-1]]
        assert streamed == want_a
        assert lines[-1]["done"] is True and lines[-1]["tokens"] == want_a
        # Recovery rebuilt with fused scheduling intact.
        assert srv.batcher.prefill_budget == 16


def test_fault_spec_parse():
    specs = FaultSpec.parse(
        "step@5:error, alloc@0:oom,insert~0.25:error,step@3:delay=1.5"
    )
    assert specs[0] == FaultSpec(site="step", kind="error", at=5)
    assert specs[1] == FaultSpec(site="alloc", kind="oom", at=0)
    assert specs[2] == FaultSpec(site="insert", kind="error", p=0.25)
    assert specs[3] == FaultSpec(
        site="step", kind="delay", at=3, delay_s=1.5
    )
    # bare site defaults to index 0
    assert FaultSpec.parse("suffix_insert:error")[0].at == 0
    for bad in ("nosite@0:error", "step@0:nope", "step@0:delay",
                "step~0.0:error", "step~1.5:error", "step"):
        with pytest.raises(ValueError):
            FaultSpec.parse(bad)


def test_injector_counts_and_raises():
    inj = FaultInjector("step@1:error,alloc@0:oom")
    inj.fire("step")                      # call 0: no match
    with pytest.raises(InjectedFault):
        inj.fire("step")                  # call 1: boom
    inj.fire("step")                      # call 2: indices fire once
    with pytest.raises(InjectedOOM):
        inj.fire("alloc")
    assert inj.calls["step"] == 3 and inj.calls["alloc"] == 1
    st = inj.stats()
    assert st["faults_injected_total"] == 2
    assert st["faults_injected_step_total"] == 1
    assert st["faults_injected_alloc_total"] == 1


def test_injector_probability_is_seeded():
    def pattern(seed):
        inj = FaultInjector("step~0.5:error", seed=seed)
        out = []
        for _ in range(64):
            try:
                inj.fire("step")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b, c = pattern(7), pattern(7), pattern(8)
    assert a == b                # deterministic per seed
    assert a != c                # varies across seeds
    assert 0 < sum(a) < 64       # actually probabilistic


def test_injector_delay(monkeypatch):
    import jax_llama_tpu.faults as faults_mod

    slept = []
    monkeypatch.setattr(faults_mod.time, "sleep", slept.append)
    inj = FaultInjector("step@0:delay=0.75")
    inj.fire("step")
    inj.fire("step")
    assert slept == [0.75]
    assert inj.delays_total == 1
    assert inj.injected_total == 0  # delays are not failures


# ---------------------------------------------------------------------------
# Batcher-level rebuild + replay (the recovery primitive, no HTTP)
# ---------------------------------------------------------------------------

def test_rebuild_replay_continues_greedy_exactly(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rid = cb.submit(list(PROMPTS[0]), max_new_tokens=10)
    want = cb.run_to_completion()[rid]

    cb2 = cb.rebuild()
    assert cb2.block_size == cb.block_size
    assert cb2.n_blocks == cb.n_blocks
    cb2.submit(list(PROMPTS[0]), max_new_tokens=10)
    got = []
    for _ in range(4):  # partial progress, then "crash"
        for ev in cb2.step():
            got.append(ev[1])
    assert 0 < len(got) < 10
    cb3 = cb2.rebuild()
    rid3 = cb3.submit(
        list(PROMPTS[0]) + got, max_new_tokens=10 - len(got)
    )
    got += cb3.run_to_completion()[rid3]
    assert got == want


def test_default_seed_matches_submit_derivation(model):
    """A replayed request pinned to default_seed(rid) draws the same key
    words submit's implicit derivation would."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64, seed=9)
    rid = cb.submit([4, 5, 6], max_new_tokens=2, temperature=0.8)
    req = cb.queue[0]
    implicit = cb._request_key(req)
    import dataclasses as _dc
    explicit = cb._request_key(
        _dc.replace(req, seed=cb.default_seed(rid))
    )
    assert (implicit == explicit).all()


# ---------------------------------------------------------------------------
# The acceptance path: mid-decode kill, every request completes identically
# ---------------------------------------------------------------------------

def test_mid_decode_fault_all_requests_complete_identically(
    model, reference
):
    """Kill the engine mid-decode (step dispatch #3 raises a device-style
    error) with blocking, streaming, and queued requests live: recovery
    rebuilds the batcher and replays, every request completes, greedy
    outputs are identical to the fault-free run, and the streaming
    client sees each token exactly once."""
    params, config = model
    inj = FaultInjector("step@3:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                if i == 0:  # one streaming client
                    results[i] = _stream_lines(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW,
                         "stream": True},
                    )
                else:
                    _, body = _post(
                        srv.address,
                        {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                    )
                    results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001 — fail the test, not the thread
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)

        lines = results[0]
        assert isinstance(lines, list), lines
        streamed = [ln["token"] for ln in lines[:-1]]
        assert streamed == reference[0]          # no dup, no gap
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == reference[0]
        for i in range(1, len(PROMPTS)):
            assert results[i] == reference[i], i

        assert inj.injected_total == 1
        assert srv.recoveries_total == 1
        _, mtext = _get(srv.address, "/metrics")
        assert "llm_server_recoveries_total 1" in mtext
        assert "llm_faults_injected_total 1" in mtext
        assert "llm_watchdog_stalls_total 0" in mtext
        _, htext = _get(srv.address, "/healthz")
        h = json.loads(htext)
        assert h["ok"] is True and h["recoveries_total"] == 1
        assert h["stalled"] is False and "last_step_age_s" in h


@pytest.mark.parametrize(
    "spec", ["insert@0:error", "step@2:error", "alloc@1:oom"]
)
def test_fault_matrix_recovers(model, reference, spec):
    """CPU fault matrix: inject at admission (the batched prefill
    dispatch), mid-decode, and during block allocation — recovery keeps
    every request's greedy output identical to the fault-free run."""
    params, config = model
    inj = FaultInjector(spec)
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                _, body = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )
                results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        for i in range(len(PROMPTS)):
            assert results[i] == reference[i], (spec, i)
        assert inj.injected_total == 1
        assert srv.recoveries_total == 1


def test_suffix_insert_fault_recovers(model):
    """The prefix-cache-hit admission dispatch dies: recovery replays the
    request through a cold batcher's full-prefill path — same tokens (a
    hit changes what is computed, never what is emitted)."""
    params, config = model
    rng = np.random.RandomState(3)
    base = rng.randint(1, 128, size=40).tolist()  # 2 full keyed blocks
    p1, p2 = base + [3], base + [9, 4]

    cb0 = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                            block_size=16)
    r1 = cb0.submit(list(p1), max_new_tokens=6)
    want1 = cb0.run_to_completion()[r1]
    r2 = cb0.submit(list(p2), max_new_tokens=6)  # suffix-path hit
    want2 = cb0.run_to_completion()[r2]
    assert cb0.stats()["prefix_requests_hit_total"] == 1

    inj = FaultInjector("suffix_insert@0:error")
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                           block_size=16, fault_injector=inj)
    with LLMServer(cb) as srv:
        _, body1 = _post(
            srv.address, {"prompt": p1, "max_new_tokens": 6}
        )
        assert body1["tokens"] == want1
        _, body2 = _post(
            srv.address, {"prompt": p2, "max_new_tokens": 6}
        )
        assert body2["tokens"] == want2
        assert inj.injected["suffix_insert"] == 1
        assert srv.recoveries_total == 1


# ---------------------------------------------------------------------------
# Host-tier swap-ins (site kv_swap) — radix index + host-DRAM tier
# ---------------------------------------------------------------------------

def _demoted_tier_batcher(model, injector=None, **kw):
    """Radix + host-tier batcher whose ``session`` chain has been
    demoted into the tier (seed the chain, then run a filler whose
    reservation needs every free block plus the idle chain)."""
    params, config = model
    rng = np.random.RandomState(71)
    session = rng.randint(1, 128, size=40).tolist()  # 2 keyed blocks
    kwargs = dict(
        n_slots=2, max_len=128, block_size=16, n_blocks=8,
        prefix_cache=True, host_kv_blocks=4, fault_injector=injector,
    )
    kwargs.update(kw)
    cb = ContinuousBatcher(params, config, **kwargs)
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    cb.submit(rng.randint(1, 128, size=112).tolist(), max_new_tokens=8)
    cb.run_to_completion()
    assert cb.stats()["host_tier_blocks"] >= 2
    return cb, session


@pytest.mark.kvcache
def test_kv_swap_fault_fails_only_restoring_request(model):
    """An injected ``kv_swap`` fault is CONTAINED: the restoring
    request fails with a clean HTTP 500 (via ``pop_failed``, exactly
    like the non-finite guard), its claims are released and the host
    slabs unpinned, a concurrent request completes untouched, the
    server never burns crash-recovery budget — and a RETRY of the same
    session swaps in fine (the slabs survived the failed attempt)."""
    params, config = model
    inj = FaultInjector("kv_swap@0:error")
    cb, session = _demoted_tier_batcher(model, injector=inj)
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=16)
    cw = cold.submit(list(session), max_new_tokens=6)
    want = cold.run_to_completion()[cw]
    ow = cold.submit(list(PROMPTS[0]), max_new_tokens=MAX_NEW)
    want_other = cold.run_to_completion()[ow]

    with LLMServer(cb) as srv:
        try:
            _post(srv.address,
                  {"prompt": session, "max_new_tokens": 6})
            assert False, "expected a 500"
        except urllib.error.HTTPError as e:
            assert e.code == 500
            assert b"swap-in failed" in e.read()
        # The failure was contained: other traffic unaffected, no
        # recovery burned, loop healthy.
        _, body = _post(
            srv.address,
            {"prompt": list(PROMPTS[0]), "max_new_tokens": MAX_NEW},
        )
        assert body["tokens"] == want_other
        assert srv.recoveries_total == 0
        code, _ = _get(srv.address, "/healthz")
        assert code == 200
        # Blocks unpinned, slabs intact: the retry restores and emits
        # exactly the cold tokens.
        _, body2 = _post(
            srv.address, {"prompt": session, "max_new_tokens": 6}
        )
        assert body2["tokens"] == want
        assert srv.batcher.stats()["swap_failures_total"] == 1
        assert srv.batcher.stats()["swap_ins_total"] == 1
        assert inj.injected["kv_swap"] == 1
        # Nothing leaked: no dangling refcounts on the batcher.
        assert not srv.batcher._block_refs or any(
            s is not None for s in srv.batcher.slots.values()
        )


@pytest.mark.kvcache
@pytest.mark.chaos
@pytest.mark.slow
def test_crash_recovery_replay_with_radix_and_host_tier(model):
    """A generic step fault mid-decode of a RESTORED session recovers
    token-identically: the rebuilt batcher's index and tier start
    empty, the replay re-prefills cold (prompt + delivered tokens),
    and greedy output matches the fault-free run — the radix index and
    host tier never change what is emitted, even across a crash."""
    params, config = model
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=16)
    cb0, session = _demoted_tier_batcher(model)
    cw = cold.submit(list(session), max_new_tokens=8)
    want = cold.run_to_completion()[cw]
    # Fault-free tier run sanity: restored == cold.
    rid = cb0.submit(list(session), max_new_tokens=8)
    assert cb0.run_to_completion()[rid] == want

    inj = FaultInjector("step@5:error")
    cb, session = _demoted_tier_batcher(model)
    # Arm AFTER the demotion choreography (its drains consume step
    # indices); the server run starts at the injector's zero.
    cb.fault_injector = inj
    with LLMServer(cb) as srv:
        _, body = _post(
            srv.address, {"prompt": session, "max_new_tokens": 8}
        )
        assert body["tokens"] == want
        assert inj.injected["step"] == 1
        assert srv.recoveries_total == 1
        # The rebuild preserved the KV-capacity configuration.
        assert srv.batcher.prefix_index == "radix"
        assert srv.batcher.host_kv_blocks == 4


def test_kv_swap_spec_parse_roundtrip():
    specs = FaultSpec.parse("kv_swap@2:error,kv_swap~0.5:oom")
    assert specs[0] == FaultSpec(site="kv_swap", kind="error", at=2)
    assert specs[1] == FaultSpec(site="kv_swap", kind="oom", p=0.5)


# ---------------------------------------------------------------------------
# Circuit breaker: hard drain past the budget
# ---------------------------------------------------------------------------

def test_recovery_budget_exhausted_drains_with_503(model):
    """Every step faults: after max_recoveries rebuilds the loop gives
    up — all in-flight clients get 503, no handler thread hangs, new
    requests are refused, and /healthz reports the dead loop."""
    params, config = model
    inj = FaultInjector("step~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    codes = {}
    with LLMServer(cb, max_recoveries=2, recovery_window_s=60.0) as srv:
        def call(i):
            try:
                codes[i] = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": 4},
                    timeout=300,
                )[0]
            except urllib.error.HTTPError as e:
                codes[i] = e.code

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)  # nobody hangs
        assert codes == {0: 503, 1: 503}

        # the loop is dead: new work is refused up front
        try:
            _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 2})
            assert False, "expected HTTP 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503

        # degraded health: loop dead, recovery counters exposed
        try:
            _get(srv.address, "/healthz")
            assert False, "expected HTTP 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            h = json.loads(e.read())
            assert h["ok"] is False and h["loop_alive"] is False
            assert h["recoveries_total"] == 2

        _, mtext = _get(srv.address, "/metrics")
        assert "llm_server_recoveries_total 2" in mtext
        assert inj.injected_total == 3  # 2 recovered + 1 fatal
    assert srv.recoveries_total == 2


# ---------------------------------------------------------------------------
# Step watchdog
# ---------------------------------------------------------------------------

def test_watchdog_flags_stall_and_clears(model):
    """A 2 s injected stall in one step flips /healthz to a degraded
    payload (stalled, last-step age) while the loop is wedged, and
    clears it once steps resume; /metrics counts the stall."""
    params, config = model
    # step@5: the warm-up request consumes steps 0-1, so the stall lands
    # mid-generation of the observed request.
    inj = FaultInjector("step@5:delay=2.0")
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, fault_injector=inj
    )
    with LLMServer(
        cb, watchdog_deadline_s=0.4, watchdog_interval_s=0.05
    ) as srv:
        # Warm the compile caches so the injected delay is the only
        # multi-second step.
        status, _ = _post(
            srv.address, {"prompt": [4, 5], "max_new_tokens": 2}
        )
        assert status == 200

        result = {}

        def call():
            result["r"] = _post(
                srv.address,
                {"prompt": [7, 8, 9], "max_new_tokens": 6}, timeout=300,
            )

        t = threading.Thread(target=call)
        t.start()
        seen_degraded = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not seen_degraded:
            try:
                _get(srv.address, "/healthz", timeout=30)
            except urllib.error.HTTPError as e:
                assert e.code == 503
                h = json.loads(e.read())
                if h["stalled"]:
                    assert h["last_step_age_s"] >= 0.4
                    assert h["loop_alive"] is True  # wedged, not dead
                    seen_degraded = True
            time.sleep(0.02)
        t.join(timeout=300)
        assert not t.is_alive()
        assert seen_degraded, "watchdog never flagged the stalled step"
        status, body = result["r"]
        assert status == 200 and len(body["tokens"]) == 6

        # the stall clears once the loop beats again
        cleared = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not cleared:
            try:
                status, htext = _get(srv.address, "/healthz", timeout=30)
                cleared = json.loads(htext)["ok"] is True
            except urllib.error.HTTPError:
                time.sleep(0.05)
        assert cleared
        _, mtext = _get(srv.address, "/metrics")
        # >= 1: the warm-up request's first-step compile may itself have
        # outlived the (deliberately tight) deadline and counted a stall.
        stalls = next(
            float(line.split()[1]) for line in mtext.splitlines()
            if line.startswith("llm_watchdog_stalls_total")
        )
        assert stalls >= 1
        assert "llm_watchdog_stalled 0" in mtext
        assert inj.delays_total == 1


# ---------------------------------------------------------------------------
# run.py wiring
# ---------------------------------------------------------------------------

def test_run_cli_fault_flags(tmp_path, capsys, monkeypatch):
    """--inject-faults arms an injector on the server's batcher; a
    mid-decode kill recovers transparently and the counters surface in
    /metrics and /healthz."""
    import sys

    from jax_llama_tpu.convert.checkpoint import save_checkpoint
    import jax_llama_tpu.run as run_cli

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    hits = {}

    def hook(srv):
        _, body = _post(
            srv.address,
            {"text": "hi", "max_new_tokens": 6, "temperature": 0.0},
        )
        hits["gen"] = body
        hits["metrics"] = _get(srv.address, "/metrics")[1]
        hits["health"] = json.loads(_get(srv.address, "/healthz")[1])

    orig = run_cli._serve_http
    monkeypatch.setattr(
        run_cli, "_serve_http",
        lambda *a, **kw: orig(*a, **kw, _test_hook=hook),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--http", "0", "--max-gen-len", "8",
         "--temperature", "0.0", "--inject-faults", "step@2:error",
         "--watchdog-s", "30"],
    )
    run_cli.main()
    out = capsys.readouterr().out
    assert "faults_armed" in out  # StructuredLogger line
    assert len(hits["gen"]["tokens"]) == 6
    assert "llm_faults_injected_total 1" in hits["metrics"]
    assert "llm_server_recoveries_total 1" in hits["metrics"]
    assert hits["health"]["ok"] is True
    assert hits["health"]["recoveries_total"] == 1


def test_replay_truncation_is_surfaced(model):
    """A request admitted within a block of capacity can lose budget on
    replay (prompt + delivered tokens pad to an extra block, eating the
    headroom): the reply must carry "truncated": true rather than pose
    as the full fault-free completion."""
    params, config = model
    inj = FaultInjector("step@2:error")
    # 48-token prompt + max_new 16 fills max_len 64 exactly at block 16;
    # any delivered token pushes the replay prompt into a 5th block.
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           block_size=16, fault_injector=inj)
    prompt = np.random.RandomState(9).randint(1, 128, size=48).tolist()
    with LLMServer(cb) as srv:
        _, body = _post(
            srv.address, {"prompt": prompt, "max_new_tokens": 16}
        )
        assert body["truncated"] is True
        assert 0 < len(body["tokens"]) < 16
        assert srv.recoveries_total == 1
    # The common case stays truncation-free (pinned by the identity
    # assertions in the tests above — no "truncated" key at all).


def test_run_cli_inject_faults_requires_http(tmp_path, monkeypatch):
    """--inject-faults without --http must refuse loudly (the non-HTTP
    modes have no recovery; a silent no-op would fake a passing drill)."""
    import sys

    import jax_llama_tpu.run as run_cli

    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
         "--inject-faults", "step@0:error"],
    )
    with pytest.raises(SystemExit, match="inject-faults"):
        run_cli.main()

    # The env-var spelling must refuse too — a JLT_FAULTS drill the mode
    # cannot honor running fault-free would fake a passing drill.
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer"],
    )
    monkeypatch.setenv("JLT_FAULTS", "step@0:error")
    with pytest.raises(SystemExit, match="JLT_FAULTS"):
        run_cli.main()


# ---------------------------------------------------------------------------
# Replica-router fault site (router.py; scale-out serving)
# ---------------------------------------------------------------------------

def test_router_replica_fault_reroutes_losslessly(model, reference):
    """Fault site ``router_replica``: the chosen replica "dies" at
    dispatch time (before any byte reaches it) — the router marks it
    unhealthy and re-routes the request to the survivor with NO token
    loss; the health poller restores the replica (it is actually fine)
    on its next sweep."""
    from jax_llama_tpu.router import ReplicaRouter

    params, config = model
    servers = [
        LLMServer(
            ContinuousBatcher(params, config, n_slots=2, max_len=64),
            replica_id=i,
        ).start()
        for i in range(2)
    ]
    inj = FaultInjector("router_replica@0:error")
    # Manual health mode: the drill asserts the IMMEDIATE unhealthy
    # mark, then drives recovery deterministically — a background
    # sweep would restore the (actually fine) replica under us.
    router = ReplicaRouter(
        servers, policy="least-loaded", fault_injector=inj,
        health_interval_s=0,
    ).start()
    try:
        st, body = _post(
            router.address,
            {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW},
        )
        assert st == 200
        assert body["tokens"] == reference[0]
        assert inj.injected["router_replica"] == 1
        h = router.health()
        assert sum(r["healthy"] for r in h["replicas"]) == 1
        m = router.metrics_text()
        assert "llm_router_reroutes_total 1" in m
        assert "llm_router_replica_failures_total 1" in m
        assert 'policy="reroute"' in m
        # The "failed" replica is actually healthy: the next health
        # sweep restores it to the routable set.
        router.check_health_now()
        assert all(
            r["healthy"] for r in router.health()["replicas"]
        )
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_router_all_replicas_down_503_with_retry_after(model):
    """Every replica unroutable -> clean 503 + Retry-After from the
    router itself (never a hang, never a connection error)."""
    from jax_llama_tpu.router import ReplicaRouter

    params, config = model
    srv = LLMServer(
        ContinuousBatcher(params, config, n_slots=2, max_len=64),
    ).start()
    router = ReplicaRouter([srv], policy="least-loaded").start()
    try:
        srv.begin_drain(timeout_s=60.0)
        router.check_health_now()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.address,
                  {"prompt": PROMPTS[0], "max_new_tokens": 2})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
    finally:
        router.stop()
        srv.stop()


def test_router_inflight_crash_replays_via_replica_recovery(
    model, reference
):
    """A mid-decode crash on the SERVING replica is handled by that
    replica's own crash-recovery (rebuild + token-identical replay) —
    the routed client sees the exact fault-free tokens, and the router
    never duplicates the request."""
    from jax_llama_tpu.router import ReplicaRouter

    params, config = model
    inj = FaultInjector("step@2:error")
    crashy = LLMServer(
        ContinuousBatcher(
            params, config, n_slots=2, max_len=64, fault_injector=inj,
        ),
        replica_id=0,
    ).start()
    steady = LLMServer(
        ContinuousBatcher(params, config, n_slots=2, max_len=64),
        replica_id=1,
    ).start()
    router = ReplicaRouter(
        [crashy, steady], policy="least-loaded",
    ).start()
    try:
        # Idle tie-break routes the first request to replica 0 — the
        # one armed to crash at its 3rd dispatch.
        st, body = _post(
            router.address,
            {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW},
        )
        assert st == 200
        assert body["tokens"] == reference[0]
        assert crashy.recoveries_total == 1
        assert steady.recoveries_total == 0
        assert inj.injected["step"] == 1
    finally:
        router.stop()
        crashy.stop()
        steady.stop()


# ---------------------------------------------------------------------------
# Elastic-fleet chaos drills: session_migrate / scale_event
# ---------------------------------------------------------------------------

# 38 tokens -> 2 full chain-key blocks at block_size=16: long enough
# for the drain to have a real session chain to migrate.
LONG_PROMPT = list(range(2, 40))


@pytest.fixture(scope="module")
def long_reference(model):
    """Fault-free greedy tokens for LONG_PROMPT (the identity oracle
    for the migration drills)."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rid = cb.submit(list(LONG_PROMPT), max_new_tokens=MAX_NEW)
    return cb.run_to_completion()[rid]


def _mk_pair(model):
    params, config = model
    return [
        LLMServer(
            ContinuousBatcher(params, config, n_slots=2, max_len=64),
            replica_id=i,
        ).start()
        for i in range(2)
    ]


@pytest.mark.chaos
def test_session_migrate_fault_aborts_move_source_intact(
    model, long_reference
):
    """Chaos drill (fault site ``session_migrate``): a fault injected
    at the start of a session's drain migration aborts THAT move only
    — the drain fails, the source RESUMES admission with its chain
    untouched, and the session keeps serving token-identically from
    the source.  The retried drain (one-shot spec consumed) migrates
    for real; after retirement exactly ONE replica serves the session
    — never both."""
    from jax_llama_tpu.router import FleetController, ReplicaRouter

    servers = _mk_pair(model)
    inj = FaultInjector("session_migrate@0:error")
    # Affinity keeps the session pinned to its source replica, so the
    # post-abort replay exercises the SOURCE (not whichever replica
    # the least-loaded tie-break lands on) and the retried drain has
    # a real chain to migrate.
    router = ReplicaRouter(
        servers, policy="affinity", health_interval_s=0,
    ).start()
    ctrl = FleetController(router, fault_injector=inj,
                           drain_timeout_s=10.0)
    try:
        # Idle tie-break pins the session to replica 0 — the victim.
        st, body = _post(
            router.address,
            {"prompt": LONG_PROMPT, "max_new_tokens": MAX_NEW},
        )
        assert st == 200 and body["tokens"] == long_reference
        router.check_health_now()
        out = ctrl.scale_down(victim=0)
        assert out["ok"] is False
        assert "migration-failures" in out["reason"]
        assert inj.injected["session_migrate"] == 1
        snap = router.health()["replicas"][0]
        assert snap["retired"] is False and snap["retiring"] is False
        # The source's chain is untouched (export never demotes
        # before destination residency is proven)...
        chains = servers[0].call_on_loop(
            lambda b: b.resident_chain_keys()
        )
        assert chains and max(len(c) for c in chains) >= 2
        # ...and the session keeps serving token-identically from it.
        st, body = _post(
            router.address,
            {"prompt": LONG_PROMPT, "max_new_tokens": MAX_NEW},
        )
        assert st == 200 and body["tokens"] == long_reference
        # Retry: the one-shot spec is consumed -> the drain completes
        # and the victim retires.
        out = ctrl.scale_down(victim=0)
        assert out["ok"] is True
        assert out["drain"]["migrated"] >= 1
        assert router.health()["replicas"][0]["retired"] is True
        # Exactly ONE replica serves the session now — never both:
        # the survivor holds the migrated chain and answers
        # token-identically.
        dst_chains = servers[1].call_on_loop(
            lambda b: b.resident_chain_keys()
        )
        assert any(len(c) >= 2 for c in dst_chains)
        st, body = _post(
            router.address,
            {"prompt": LONG_PROMPT, "max_new_tokens": MAX_NEW},
        )
        assert st == 200 and body["tokens"] == long_reference
    finally:
        ctrl.close()
        router.stop()
        for s in servers:
            s.stop()


@pytest.mark.chaos
def test_scale_event_fault_aborts_scale_action_cleanly(model):
    """Chaos drill (fault site ``scale_event``): a fault at the start
    of a scale action aborts the WHOLE action cleanly — fleet
    membership unchanged, the abort is a recorded decision — and the
    retried action proceeds."""
    from jax_llama_tpu.router import FleetController, ReplicaRouter

    servers = _mk_pair(model)
    inj = FaultInjector("scale_event@0:error")
    router = ReplicaRouter(
        servers, policy="least-loaded", health_interval_s=0,
    ).start()
    ctrl = FleetController(router, fault_injector=inj)
    try:
        router.check_health_now()
        out = ctrl.scale_down(victim=0)
        assert out["ok"] is False
        assert inj.injected["scale_event"] == 1
        snaps = router.health()["replicas"]
        assert len(snaps) == 2
        assert all(
            not s["retired"] and not s["retiring"] for s in snaps
        )
        assert ctrl.metrics_snapshot()["scale_events"]["aborted"] == 1
        evs = [
            e for e in router.decisions.json(
                n=16, kind="scale")["decisions"]
            if e.get("action") == "aborted"
        ]
        assert evs and evs[-1]["op"] == "down"
        # The one-shot spec is consumed: the retry proceeds cleanly.
        out = ctrl.scale_down(victim=0)
        assert out["ok"] is True
        assert router.health()["replicas"][0]["retired"] is True
    finally:
        ctrl.close()
        router.stop()
        for s in servers:
            s.stop()
