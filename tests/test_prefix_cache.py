"""Prefix caching in the paged-pool batcher (r5; beyond-reference serving
depth — the reference has no serving at all).

The invariants pinned here:
  * a prefix-cache hit changes WHAT IS COMPUTED, never what is emitted —
    outputs are token-identical to a cold batcher, greedy and sampled;
  * hits actually happen (stats counters) and reuse whole blocks;
  * retained blocks are evicted under allocation pressure without
    corrupting later requests (the stale-position hazard);
  * refcounted sharing frees a block only after its last user finishes.
"""

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=256, dtype="float32", param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def test_sequential_hit_token_identical_and_counted(model):
    """The /chat pattern: the same long system prompt resubmitted after
    the first request completed must HIT (retained blocks) and emit
    exactly the cold batcher's tokens — greedy and seeded-sampled."""
    params, config = model
    rng = np.random.RandomState(0)
    system = rng.randint(1, 128, size=40).tolist()  # 2.5 blocks of 16
    p1 = system + rng.randint(1, 128, size=5).tolist()
    p2 = system + rng.randint(1, 128, size=7).tolist()

    submits = [
        (p1, dict(max_new_tokens=8)),
        (p2, dict(max_new_tokens=8, temperature=0.8, seed=7)),
    ]
    # Cold: prefix cache disabled entirely.
    cb0 = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                            block_size=16, prefix_cache=False)
    cold_out = []
    for p, kw in submits:
        rid = cb0.submit(list(p), **kw)
        cold_out.append(cb0.run_to_completion()[rid])

    # Warm: sequential submits through one slot; the second shares the
    # system prompt's two full blocks (40 tokens -> blocks 0,1 full;
    # the divergence happens inside block 2).
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                           block_size=16, prefix_cache=True)
    warm_out = []
    for p, kw in submits:
        rid = cb.submit(list(p), **kw)
        warm_out.append(cb.run_to_completion()[rid])

    assert warm_out == cold_out
    st = cb.stats()
    assert st["prefix_requests_hit_total"] == 1
    assert st["prefix_blocks_reused_total"] == 2
    assert st["prefix_cached_blocks"] > 0  # retained after completion


def test_concurrent_share_refcounts(model):
    """Two live requests sharing a cached prefix: the block is freed only
    after BOTH finish, and outputs match the cold run."""
    params, config = model
    rng = np.random.RandomState(1)
    prefix = rng.randint(1, 128, size=32).tolist()  # 2 full blocks
    a = prefix + [3, 5]
    bq = prefix + [9]

    # Seed the cache with a first request, then submit two sharers that
    # run CONCURRENTLY (2 slots).
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, prefix_cache=True)
    r0 = cb.submit(list(prefix) + [2], max_new_tokens=4)
    out0 = cb.run_to_completion()[r0]
    assert np.isfinite(len(out0))
    ra = cb.submit(list(a), max_new_tokens=6)
    rb = cb.submit(list(bq), max_new_tokens=6)
    res = cb.run_to_completion()
    st = cb.stats()
    assert st["prefix_requests_hit_total"] == 2
    # Shared blocks survived both completions back into the cache.
    assert st["prefix_cached_blocks"] >= 1

    cold = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                             block_size=16, prefix_cache=False)
    ca = cold.submit(list(a), max_new_tokens=6)
    cbq = cold.submit(list(bq), max_new_tokens=6)
    cres = cold.run_to_completion()
    assert res[ra] == cres[ca]
    assert res[rb] == cres[cbq]


def test_eviction_under_pressure_stays_correct(model):
    """A pool sized so retained prefixes must be evicted to admit new
    work: admission succeeds (capacity counts evictable blocks) and the
    evictee's stale positions never leak into the new request."""
    params, config = model
    rng = np.random.RandomState(2)
    # Pool: exactly two reservations' worth of blocks.
    # Each request: 32-token prompt (2 blocks) + 32 max_new -> 4 blocks.
    n_blocks = 8
    prompts = [rng.randint(1, 128, size=32).tolist() for _ in range(3)]

    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           block_size=16, n_blocks=n_blocks,
                           prefix_cache=True)
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                             block_size=16, n_blocks=n_blocks,
                             prefix_cache=False)
    for p in prompts:  # sequential: each retains its prefix on completion
        rid = cb.submit(list(p), max_new_tokens=32)
        want_rid = cold.submit(list(p), max_new_tokens=32)
        got = cb.run_to_completion()[rid]
        want = cold.run_to_completion()[want_rid]
        assert got == want
    # The third admission necessarily evicted earlier retained blocks.
    assert cb.stats()["prefix_cached_blocks"] <= n_blocks


def test_cancel_sharer_keeps_other_alive(model):
    """Cancelling one of two requests sharing cached prefix blocks must
    not free or corrupt the blocks under the survivor (refcount, not
    ownership)."""
    params, config = model
    rng = np.random.RandomState(5)
    prefix = rng.randint(1, 128, size=32).tolist()
    a = prefix + [11]
    b = prefix + [22]

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, prefix_cache=True)
    cb.submit(list(prefix) + [1], max_new_tokens=2)
    cb.run_to_completion()  # seed the cache
    ra = cb.submit(list(a), max_new_tokens=8)
    rb = cb.submit(list(b), max_new_tokens=8)
    got = {ra: [], rb: []}
    for rid, tok, *_ in cb.step():  # both admitted (as hits), decoding
        got[rid].append(tok)
    assert cb.cancel(ra)
    while cb.pending():
        for rid, tok, *_ in cb.step():
            got[rid].append(tok)

    cold = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                             block_size=16, prefix_cache=False)
    cw = cold.submit(list(b), max_new_tokens=8)
    want = cold.run_to_completion()[cw]
    assert got[rb] == want
    # And a later resubmit (hitting the still-cached chain) matches too.
    rb2 = cb.submit(list(b), max_new_tokens=8)
    assert cb.run_to_completion()[rb2] == want


# slow (r06 budget rebalance, ~19 s): hit + logprobs parity is also
# pinned by test_kvcache.py's parity matrix and the multi-chunk
# suffix shape by test_suffix_admission_buckets below.
@pytest.mark.slow
def test_chunked_suffix_and_logprobs(model):
    """A hit whose remaining suffix spans multiple prefill chunks (the
    chunked gathered-view path), with logprobs on: outputs AND per-token
    logprobs identical to the cold batcher."""
    params, config = model
    rng = np.random.RandomState(4)
    prefix = rng.randint(1, 128, size=32).tolist()  # 2 full blocks
    long_suffix = rng.randint(1, 128, size=70).tolist()  # > 2 chunks of 32
    prompt = prefix + long_suffix

    def run(pc):
        cb = ContinuousBatcher(
            params, config, n_slots=1, max_len=256, block_size=16,
            prefill_chunk=32, logprobs=True, prefix_cache=pc,
        )
        # Seed the cache with a short request sharing only the prefix.
        cb.submit(list(prefix) + [7], max_new_tokens=2)
        cb.run_to_completion()
        rid = cb.submit(list(prompt), max_new_tokens=6)
        out = []
        while cb.pending():
            for tup in cb.step():
                if tup[0] == rid:
                    out.append((tup[1], round(float(tup[3]), 5)))
        return out, cb.stats()

    warm, wst = run(True)
    cold, _ = run(False)
    assert warm == cold
    assert wst["prefix_requests_hit_total"] == 1
    assert wst["prefix_blocks_reused_total"] == 2


def test_grouped_hits_with_differing_prefix_depths(model):
    """One grouped suffix-insert dispatch whose rows have DIFFERENT
    cached-prefix depths (fill0 32 vs 48) but the same padded suffix
    length: per-row offsets must be honored independently — outputs
    identical to the cold batcher for both rows."""
    params, config = model
    rng = np.random.RandomState(6)
    pref_a = rng.randint(1, 128, size=32).tolist()  # 2 full blocks
    pref_b = rng.randint(1, 128, size=48).tolist()  # 3 full blocks
    a = pref_a + rng.randint(1, 128, size=10).tolist()  # suffix pads to 16
    b = pref_b + rng.randint(1, 128, size=12).tolist()  # suffix pads to 16

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, prefix_cache=True)
    cb.submit(list(pref_a) + [1], max_new_tokens=2)
    cb.submit(list(pref_b) + [2], max_new_tokens=2)
    cb.run_to_completion()  # seed both chains
    ra = cb.submit(list(a), max_new_tokens=6)
    rb = cb.submit(list(b), max_new_tokens=6)
    res = cb.run_to_completion()
    st = cb.stats()
    assert st["prefix_requests_hit_total"] == 2
    assert st["prefix_blocks_reused_total"] == 5  # 2 + 3

    cold = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                             block_size=16, prefix_cache=False)
    ca = cold.submit(list(a), max_new_tokens=6)
    cbr = cold.submit(list(b), max_new_tokens=6)
    cres = cold.run_to_completion()
    assert res[ra] == cres[ca]
    assert res[rb] == cres[cbr]


# slow (r17 budget rebalance, ~7 s): the two composing contracts keep
# tier-1 pins — repeat-hit exactness via
# test_sequential_hit_token_identical_and_counted, speculative serving
# identity via test_serving_spec's tier-1 R cells — so the composed
# prefix-hit x spec drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_repeat_same_prompt_exact_with_spec(model):
    """Prefix hits compose with speculative decoding (draft pool shares
    the same blocks/chain): identical outputs, and the second submit of
    an identical prompt hits."""
    params, config = model
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 128, size=33).tolist()

    outs = []
    for pc in (False, True):
        cb = ContinuousBatcher(
            params, config, n_slots=1, max_len=128, block_size=16,
            draft_params=draft_params, draft_config=draft_config,
            n_draft=2, prefix_cache=pc,
        )
        got = []
        for _ in range(2):
            rid = cb.submit(list(prompt), max_new_tokens=10)
            got.append(cb.run_to_completion()[rid])
        outs.append(got)
        if pc:
            assert cb.stats()["prefix_requests_hit_total"] == 1
    assert outs[0] == outs[1]
    # Determinism across repeats too (greedy).
    assert outs[0][0] == outs[0][1]


def test_duplicate_chain_leaves_no_unreachable_blocks(model):
    """Two identical prompts in ONE cold admission burst both prefill
    fully and both publish the same chain keys.  Radix semantics
    (migrated from the pre-r06 exact-chain supersede pin): the shared
    prefix is ONE set of nodes by construction — the second
    publication leaves the existing nodes' blocks in place and its own
    duplicate copies stay unkeyed, freeing plainly with their slots.
    Nothing retained may be unreachable, refcounts must not dangle,
    and free + retained must account for the whole pool."""
    params, config = model
    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 128, size=40).tolist()  # 2 full keyed blocks

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, prefix_cache=True)
    for _ in range(2):  # repeat the burst: cold, then hitting
        r1 = cb.submit(list(prompt), max_new_tokens=4)
        r2 = cb.submit(list(prompt), max_new_tokens=4)
        res = cb.run_to_completion()
        assert set(res) >= {r1, r2}
        assert res[r1] == res[r2]
        # No dangling refcounts, exact capacity accounting, and the
        # tree holds exactly the chain's 2 nodes — the duplicate burst
        # did NOT mint a second copy of the shared prefix.
        assert not cb._block_refs
        assert (len(cb.free_blocks) + cb._store.cached_blocks()
                == cb.n_blocks)
        assert cb.stats()["radix_nodes_total"] == 2

    # Directly exercise the duplicate-publication branch: publishing a
    # fresh block for a chain whose node is already resident keeps the
    # EXISTING node's block; the fresh copy stays unkeyed (it frees
    # with its slot instead of lingering unreachable).
    store = cb._store
    key = next(iter(store._by_key))
    old_blk = store._by_key[key].block
    new_blk = cb.free_blocks[0]
    cb._register_chain([new_blk], [key])
    assert store._by_key[key].block == old_blk
    assert not store.is_keyed(new_blk)
    assert store.is_keyed(old_blk)


# slow (r17 budget rebalance, ~12 s): the bounded-executable contract is
# statically tier-1-pinned by the retrace auditor (tests/test_analysis.py
# gates the bounded jit-cache-key domains, _paged_suffix_insert
# included) and grouped-suffix token identity stays tier-1-pinned by
# test_grouped_hits_with_differing_prefix_depths; the dynamic
# compile-counting drill rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_suffix_admission_buckets_jit_executables(model):
    """Grouped suffix admission buckets the padded suffix length to a
    power of two of blocks (like admission row counts), so diverse /chat
    suffix lengths compile a BOUNDED set of _paged_suffix_insert
    executables: four hits whose block-rounded suffixes span {32, 48,
    48, 64} tokens share the {32, 64} buckets — 2 compiles, not 3 — and
    outputs stay identical to a cold batcher."""
    from jax_llama_tpu.serving import _paged_suffix_insert

    params, config = model
    rng = np.random.RandomState(12)
    base = rng.randint(1, 128, size=32).tolist()   # the shared 2 blocks
    prime = base + rng.randint(1, 128, size=16).tolist()
    extras = [rng.randint(1, 128, size=n).tolist()
              for n in (17, 33, 45, 60)]

    cb = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                           block_size=16, prefix_cache=True)
    rid = cb.submit(list(prime), max_new_tokens=2)
    cb.run_to_completion()
    before = _paged_suffix_insert._cache_size()
    got = []
    for extra in extras:
        rid = cb.submit(base + extra, max_new_tokens=4)
        got.append(cb.run_to_completion()[rid])
    assert cb.stats()["prefix_requests_hit_total"] == 4
    compiled = _paged_suffix_insert._cache_size() - before
    assert compiled == 2, compiled  # buckets {32, 64}, not {32, 48, 64}

    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=16, prefix_cache=False)
    for extra, want in zip(extras, got):
        rid = cold.submit(base + extra, max_new_tokens=4)
        assert cold.run_to_completion()[rid] == want


# NOTE: these run LAST: their admissions compile suffix-insert shapes
# that would otherwise perturb test_suffix_admission_buckets' compile
# count (the jit cache is cleared per MODULE, not per test).

def test_exact_mode_supersede_frees_idle_duplicates(model):
    """The legacy flat-map semantics survive behind
    ``prefix_index="exact"`` (the behavioral oracle): a duplicate
    publication SUPERSEDES, and re-keying a chain whose old block sits
    refcount-0 in the idle LRU frees it outright — the pre-radix pin,
    verbatim, one flag away."""
    params, config = model
    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 128, size=40).tolist()

    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, prefix_index="exact")
    r1 = cb.submit(list(prompt), max_new_tokens=4)
    r2 = cb.submit(list(prompt), max_new_tokens=4)
    res = cb.run_to_completion()
    assert res[r1] == res[r2]
    store = cb._store
    assert set(store._reusable) <= set(store._prefix_index.values())
    assert len(cb.free_blocks) + len(store._reusable) == cb.n_blocks
    assert not cb._block_refs

    key = next(iter(store._prefix_index))
    old_blk = store._prefix_index[key]
    assert old_blk in store._reusable
    new_blk = cb.free_blocks[0]
    cb._register_chain([new_blk], [key])
    assert old_blk not in store._reusable
    assert old_blk in cb.free_blocks
    assert store._prefix_index[key] == new_blk


def test_radix_partial_prefix_shared_across_divergent_chains(model):
    """The radix win the flat map could not express as sharing: three
    chains diverging AFTER a common 2-block prefix share those two
    NODES (5 nodes total, not 6+), and a fourth request extending the
    common prefix hits it at full depth — token-identically to cold."""
    params, config = model
    rng = np.random.RandomState(13)
    common = rng.randint(1, 128, size=32).tolist()   # 2 full blocks
    tails = [rng.randint(1, 128, size=18).tolist() for _ in range(3)]

    cb = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                           block_size=16, prefix_cache=True)
    for tail in tails:  # sequential: each publishes its whole chain
        rid = cb.submit(common + tail, max_new_tokens=4)
        cb.run_to_completion()
    st = cb.stats()
    # chains are keyed on blocks strictly before the last token:
    # 50 tokens -> 3 keyed blocks each; 2 shared + 3 x 1 divergent.
    assert st["radix_nodes_total"] == 5
    # Chains 2 and 3 hit the shared 2-block prefix.
    assert st["prefix_requests_hit_total"] == 2
    assert st["prefix_blocks_reused_total"] == 4

    probe = common + [3, 5, 7]
    rid = cb.submit(list(probe), max_new_tokens=6)
    got = cb.run_to_completion()[rid]
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=16, prefix_cache=False)
    cr = cold.submit(list(probe), max_new_tokens=6)
    assert got == cold.run_to_completion()[cr]
    assert cb.stats()["prefix_hit_tokens_ratio"] > 0


