"""Paged-attention decode kernel: parity vs the gathered-view reference.

The kernel (ops/paged_attention.py) walks the serving block table inside
its BlockSpec index maps; these tests pin its numerics against dense
attention over an explicitly gathered contiguous view — the path it
replaced — including dead table entries, partially-filled blocks,
inactive rows, and the model-level ``paged_forward`` step.
"""

import numpy as np
import jax
import pytest
import jax.numpy as jnp

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.models import forward
from jax_llama_tpu.models.llama import PagedKVCache
from jax_llama_tpu.ops import attention_bias, sdpa
from jax_llama_tpu.ops.paged_attention import paged_decode_attention
from jax_llama_tpu.serving import _gather_cache, init_pool


def _random_pool_state(rng, B, KVH, d, NB, BLK, MB, fills):
    kp = rng.randn(KVH, NB, BLK, d).astype(np.float32)
    vp = rng.randn(KVH, NB, BLK, d).astype(np.float32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    table = np.full((B, MB), NB, np.int32)
    free = list(range(NB))
    for b, fill in enumerate(fills):
        n = -(-fill // BLK) if fill else 0
        blocks = [free.pop(0) for _ in range(n)]
        table[b, :n] = blocks
        for j, blk in enumerate(blocks):
            m = min(BLK, fill - j * BLK)
            pool_pos[blk, :m] = np.arange(j * BLK, j * BLK + m)
    return kp, vp, pool_pos, table


def _reference(q, kn, vn, kp, vp, pool_pos, table, qpos, b):
    """Dense attention over row b's gathered blocks + the new slot."""
    NB = kp.shape[1]
    ks, vs, ps = [], [], []
    for t in table[b]:
        if t < NB:
            ks.append(kp[:, t])
            vs.append(vp[:, t])
            ps.append(pool_pos[t])
    kcat = np.concatenate(
        ks + [kn[b].transpose(1, 0, 2)], axis=1
    ).transpose(1, 0, 2)[None]
    vcat = np.concatenate(
        vs + [vn[b].transpose(1, 0, 2)], axis=1
    ).transpose(1, 0, 2)[None]
    pcat = np.concatenate(ps + [np.array([qpos[b]])])
    bias = attention_bias(
        jnp.asarray([[qpos[b]]], jnp.int32), jnp.asarray(pcat[None]),
        jnp.asarray((pcat >= 0)[None]),
    )
    return np.asarray(
        sdpa(jnp.asarray(q[b:b + 1]), jnp.asarray(kcat), jnp.asarray(vcat),
             bias)
    )[0]


def test_paged_kernel_matches_gathered_dense():
    rng = np.random.RandomState(0)
    B, H, KVH, d = 4, 8, 2, 32
    NB, BLK, MB = 12, 16, 5
    # row fills: multi-block, empty (inactive), one block, partial block
    fills = [40, 0, 16, 7]
    qpos = np.array([40, -1, 16, 7], np.int32)
    kp, vp, pool_pos, table = _random_pool_state(
        rng, B, KVH, d, NB, BLK, MB, fills
    )
    q = rng.randn(B, 1, H, d).astype(np.float32)
    kn = rng.randn(B, 1, KVH, d).astype(np.float32)
    vn = rng.randn(B, 1, KVH, d).astype(np.float32)

    got = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    assert np.isfinite(got).all()
    for b in range(B):
        if qpos[b] < 0:
            continue  # inactive row: output is ignored by the host
        want = _reference(q, kn, vn, kp, vp, pool_pos, table, qpos, b)
        np.testing.assert_allclose(got[b], want, atol=1e-5, rtol=1e-5)


def test_paged_kernel_gqa_head_order():
    """Query head h must read KV head h // group (the model's layout)."""
    rng = np.random.RandomState(1)
    B, H, KVH, d = 1, 4, 2, 16
    NB, BLK, MB = 4, 8, 2
    fills = [12]
    qpos = np.array([12], np.int32)
    kp, vp, pool_pos, table = _random_pool_state(
        rng, B, KVH, d, NB, BLK, MB, fills
    )
    q = rng.randn(B, 1, H, d).astype(np.float32)
    kn = rng.randn(B, 1, KVH, d).astype(np.float32)
    vn = rng.randn(B, 1, KVH, d).astype(np.float32)
    got = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    want = _reference(q, kn, vn, kp, vp, pool_pos, table, qpos, 0)
    np.testing.assert_allclose(got[0], want, atol=1e-5, rtol=1e-5)


def _reference_multi(q, kn, vn, kp, vp, pool_pos, table, qpos, b, T):
    """Dense attention over row b's gathered blocks + T new slots at
    consecutive positions qpos..qpos+T-1 (within-step causal)."""
    NB = kp.shape[1]
    ks, vs, ps = [], [], []
    for t in table[b]:
        if t < NB:
            ks.append(kp[:, t])
            vs.append(vp[:, t])
            ps.append(pool_pos[t])
    kcat = np.concatenate(
        ks + [kn[b].transpose(1, 0, 2)], axis=1
    ).transpose(1, 0, 2)[None]
    vcat = np.concatenate(
        vs + [vn[b].transpose(1, 0, 2)], axis=1
    ).transpose(1, 0, 2)[None]
    new_pos = qpos[b] + np.arange(T)
    pcat = np.concatenate(ps + [new_pos])
    q_positions = (qpos[b] + np.arange(T))[None]
    bias = attention_bias(
        jnp.asarray(q_positions, jnp.int32), jnp.asarray(pcat[None]),
        jnp.asarray((pcat >= 0)[None]),
    )
    return np.asarray(
        sdpa(jnp.asarray(q[b:b + 1]), jnp.asarray(kcat), jnp.asarray(vcat),
             bias)
    )[0]


def test_paged_kernel_multi_token_matches_dense():
    """T>1 (speculative-verify shape): T consecutive-position queries per
    row share one pool sweep; token t additionally attends the step's own
    slots j <= t.  Must match dense attention over the gathered blocks +
    new slots, including rows whose early tokens see fewer blocks."""
    rng = np.random.RandomState(7)
    B, H, KVH, d, T = 4, 8, 2, 32, 3
    NB, BLK, MB = 12, 16, 5
    fills = [40, 0, 16, 7]
    qpos = np.array([40, -1, 16, 7], np.int32)
    kp, vp, pool_pos, table = _random_pool_state(
        rng, B, KVH, d, NB, BLK, MB, fills
    )
    q = rng.randn(B, T, H, d).astype(np.float32)
    kn = rng.randn(B, T, KVH, d).astype(np.float32)
    vn = rng.randn(B, T, KVH, d).astype(np.float32)

    got = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    assert np.isfinite(got).all()
    for b in range(B):
        if qpos[b] < 0:
            continue
        want = _reference_multi(
            q, kn, vn, kp, vp, pool_pos, table, qpos, b, T
        )
        np.testing.assert_allclose(got[b], want, atol=1e-5, rtol=1e-5)


def test_paged_kernel_multi_token_first_token_empty_pool():
    """A fresh row (empty pool, qpos 0): token 0 attends only itself —
    the all-masked-tile guard must not poison its softmax state."""
    rng = np.random.RandomState(8)
    B, H, KVH, d, T = 2, 4, 2, 16, 4
    NB, BLK, MB = 6, 8, 3
    fills = [0, 11]
    qpos = np.array([0, 11], np.int32)
    kp, vp, pool_pos, table = _random_pool_state(
        rng, B, KVH, d, NB, BLK, MB, fills
    )
    # Row 0: reserve blocks but nothing written yet (pos stays -1).
    table[0, :2] = [4, 5]
    q = rng.randn(B, T, H, d).astype(np.float32)
    kn = rng.randn(B, T, KVH, d).astype(np.float32)
    vn = rng.randn(B, T, KVH, d).astype(np.float32)
    got = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    assert np.isfinite(got).all()
    for b in range(B):
        want = _reference_multi(
            q, kn, vn, kp, vp, pool_pos, table, qpos, b, T
        )
        np.testing.assert_allclose(got[b], want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_paged_forward_multi_token_matches_gathered_view():
    """paged_forward at T=3 (the verify shape) vs the gathered-view
    forward: same logits for active rows, same pool afterwards."""
    import dataclasses

    from jax_llama_tpu.serving import _scatter_back

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, NB, BLK, MB, T = 3, 10, 8, 3, 3
    pool = init_pool(config, NB, BLK)
    rng = np.random.RandomState(9)
    pool = dataclasses.replace(
        pool,
        k=jnp.asarray(rng.randn(*pool.k.shape), pool.k.dtype),
        v=jnp.asarray(rng.randn(*pool.v.shape), pool.v.dtype),
    )
    fills = [10, 0, 17]
    qpos = np.array([10, -1, 17], np.int32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    table = np.full((B, MB), NB, np.int32)
    free = list(range(NB))
    n_alloc = np.zeros((B,), np.int32)
    for b, fill in enumerate(fills):
        n = -(-(fill + T) // BLK) if qpos[b] >= 0 else 0
        blocks = [free.pop(0) for _ in range(n)]
        table[b, :n] = blocks
        n_alloc[b] = n
        for j, blk in enumerate(blocks):
            m = max(0, min(BLK, fill - j * BLK))
            if m:
                pool_pos[blk, :m] = np.arange(j * BLK, j * BLK + m)
    pool = dataclasses.replace(pool, pos=jnp.asarray(pool_pos))

    toks = jnp.asarray(rng.randint(0, 128, (B, T)), jnp.int32)
    active = jnp.asarray(qpos >= 0)
    positions = jnp.asarray(
        np.where((qpos >= 0)[:, None], qpos[:, None] + np.arange(T), -1),
        jnp.int32,
    )
    fill_arr = jnp.asarray(fills, jnp.int32)
    tbl = jnp.asarray(table)
    amask = jnp.broadcast_to(active[:, None], (B, T))

    view = _gather_cache(pool, tbl, jnp.asarray(n_alloc), fill_arr)
    want_logits, view = forward(
        params, toks, positions, config, cache=view, attn_mask=amask,
    )
    want_pool = _scatter_back(pool, view, tbl, fill_arr, active, T=T)

    pcache = PagedKVCache(
        k=pool.k, v=pool.v, pos=pool.pos, table=tbl, fill=fill_arr
    )
    got_logits, pcache = forward(
        params, toks, positions, config, cache=pcache, attn_mask=amask,
    )

    act = np.asarray(active)
    np.testing.assert_allclose(
        np.asarray(got_logits)[act], np.asarray(want_logits)[act],
        atol=1e-4, rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(pcache.k), np.asarray(want_pool.k), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(pcache.pos), np.asarray(want_pool.pos)
    )


def test_paged_forward_matches_gathered_view_forward():
    """A full model step via paged_forward (Pallas kernel + scatter) must
    match the gathered-view forward (per-row-offset KVCache) it replaced:
    same logits, and the pool ends in the same state."""
    import dataclasses

    from jax_llama_tpu.serving import _scatter_back

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, NB, BLK, MB = 3, 8, 8, 3
    pool = init_pool(config, NB, BLK)
    rng = np.random.RandomState(2)
    # Fill pools with random content + consistent positions.
    pool = dataclasses.replace(
        pool,
        k=jnp.asarray(rng.randn(*pool.k.shape), pool.k.dtype),
        v=jnp.asarray(rng.randn(*pool.v.shape), pool.v.dtype),
    )
    fills = [10, 0, 17]
    qpos = np.array([10, -1, 17], np.int32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    table = np.full((B, MB), NB, np.int32)
    free = list(range(NB))
    n_alloc = np.zeros((B,), np.int32)
    for b, fill in enumerate(fills):
        n = -(-fill // BLK) if fill else 0
        blocks = [free.pop(0) for _ in range(n)]
        table[b, :n] = blocks
        n_alloc[b] = n
        for j, blk in enumerate(blocks):
            m = min(BLK, fill - j * BLK)
            pool_pos[blk, :m] = np.arange(j * BLK, j * BLK + m)
    pool = dataclasses.replace(pool, pos=jnp.asarray(pool_pos))

    tau = jnp.asarray(rng.randint(0, 128, (B,)), jnp.int32)
    active = jnp.asarray(qpos >= 0)
    positions = jnp.asarray(qpos, jnp.int32)[:, None]
    fill_arr = jnp.asarray(fills, jnp.int32)
    tbl = jnp.asarray(table)

    # Gathered-view path.
    view = _gather_cache(pool, tbl, jnp.asarray(n_alloc), fill_arr)
    want_logits, view = forward(
        params, tau[:, None], positions, config, cache=view,
        attn_mask=active[:, None],
    )
    want_pool = _scatter_back(pool, view, tbl, fill_arr, active, T=1)

    # Paged kernel path.
    pcache = PagedKVCache(
        k=pool.k, v=pool.v, pos=pool.pos, table=tbl, fill=fill_arr
    )
    got_logits, pcache = forward(
        params, tau[:, None], positions, config, cache=pcache,
        attn_mask=active[:, None],
    )

    act = np.asarray(active)
    np.testing.assert_allclose(
        np.asarray(got_logits)[act], np.asarray(want_logits)[act],
        atol=1e-4, rtol=1e-4,
    )
    np.testing.assert_allclose(
        np.asarray(pcache.k), np.asarray(want_pool.k), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(pcache.v), np.asarray(want_pool.v), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(pcache.pos), np.asarray(want_pool.pos)
    )


def test_paged_kernel_all_dead_block_contributes_nothing():
    """A table entry whose block holds only pos=-1 slots (e.g. a
    reserved-but-unwritten block, or a hole) must be SKIPPED — processing
    it would add p = exp(MASK - MASK) = 1 garbage into the softmax
    state.  Construct a row whose FIRST block is all-dead so the guard,
    not a lucky earlier live block, is what protects the output."""
    rng = np.random.RandomState(4)
    KVH, d = 2, 16
    NB, BLK, MB = 6, 8, 3
    kp = rng.randn(KVH, NB, BLK, d).astype(np.float32)
    vp = rng.randn(KVH, NB, BLK, d).astype(np.float32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    # Row 0: table [deadblk, liveblk, sentinel] — block 0 all-dead,
    # block 1 holds positions 8..15 (as if the hole were rolled back).
    pool_pos[1, :] = np.arange(8, 16)
    table = np.array([[0, 1, NB]], np.int32)
    qpos = np.array([16], np.int32)
    q = rng.randn(1, 1, 4, d).astype(np.float32)
    kn = rng.randn(1, 1, KVH, d).astype(np.float32)
    vn = rng.randn(1, 1, KVH, d).astype(np.float32)
    got = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kn), jnp.asarray(vn),
        jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(pool_pos),
        jnp.asarray(table), jnp.asarray(qpos),
    ))
    # Reference: only block 1's slots + the new token.
    ks = np.concatenate([kp[:, 1], kn[0].transpose(1, 0, 2)], axis=1)
    vs = np.concatenate([vp[:, 1], vn[0].transpose(1, 0, 2)], axis=1)
    ps = np.concatenate([pool_pos[1], [16]])
    bias = attention_bias(
        jnp.asarray([[16]], jnp.int32), jnp.asarray(ps[None]),
        jnp.asarray((ps >= 0)[None]),
    )
    want = np.asarray(sdpa(
        jnp.asarray(q), jnp.asarray(ks.transpose(1, 0, 2)[None]),
        jnp.asarray(vs.transpose(1, 0, 2)[None]), bias,
    ))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_paged_forward_int8_matches_gathered_int8():
    """int8 pool through the kernel (in-kernel scale folding) must match
    the gathered-view int8 path: same logits at quantization-noise level,
    bit-equal scattered payload + scales (both quantize the same
    projections with the same math)."""
    import dataclasses

    from jax_llama_tpu.serving import _scatter_back
    from jax_llama_tpu.models.llama import quantize_kv

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64, kv_cache_dtype="int8",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, NB, BLK, MB = 2, 6, 8, 3
    pool = init_pool(config, NB, BLK)
    assert pool.quantized and pool.k.dtype == jnp.int8
    rng = np.random.RandomState(5)
    # Populate with quantized random content + matching scales.
    kf = rng.randn(*pool.k.shape).astype(np.float32)
    vf = rng.randn(*pool.v.shape).astype(np.float32)
    kq, ks = quantize_kv(jnp.asarray(kf))
    vq, vs = quantize_kv(jnp.asarray(vf))
    fills = [12, 20]
    qpos = np.array(fills, np.int32)
    pool_pos = np.full((NB, BLK), -1, np.int32)
    table = np.full((B, MB), NB, np.int32)
    free = list(range(NB))
    n_alloc = np.zeros((B,), np.int32)
    for b, fill in enumerate(fills):
        n = -(-fill // BLK)
        blocks = [free.pop(0) for _ in range(n)]
        table[b, :n] = blocks
        n_alloc[b] = n
        for j, blk in enumerate(blocks):
            m = min(BLK, fill - j * BLK)
            pool_pos[blk, :m] = np.arange(j * BLK, j * BLK + m)
    pool = dataclasses.replace(
        pool, k=kq, v=vq, k_scale=ks, v_scale=vs,
        pos=jnp.asarray(pool_pos),
    )

    tau = jnp.asarray(rng.randint(0, 128, (B,)), jnp.int32)
    active = jnp.ones((B,), bool)
    positions = jnp.asarray(qpos, jnp.int32)[:, None]
    fill_arr = jnp.asarray(fills, jnp.int32)
    tbl = jnp.asarray(table)

    view = _gather_cache(pool, tbl, jnp.asarray(n_alloc), fill_arr)
    want_logits, view = forward(
        params, tau[:, None], positions, config, cache=view,
        attn_mask=active[:, None],
    )
    want_pool = _scatter_back(pool, view, tbl, fill_arr, active, T=1)

    pcache = PagedKVCache(
        k=pool.k, v=pool.v, pos=pool.pos, table=tbl, fill=fill_arr,
        k_scale=pool.k_scale, v_scale=pool.v_scale,
    )
    got_logits, pcache = forward(
        params, tau[:, None], positions, config, cache=pcache,
        attn_mask=active[:, None],
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits),
        atol=2e-4, rtol=2e-4,
    )
    np.testing.assert_array_equal(np.asarray(pcache.k), np.asarray(want_pool.k))
    np.testing.assert_array_equal(np.asarray(pcache.v), np.asarray(want_pool.v))
    np.testing.assert_allclose(
        np.asarray(pcache.k_scale), np.asarray(want_pool.k_scale), rtol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(pcache.pos), np.asarray(want_pool.pos)
    )


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_int8_batcher_kernel_path_runs_end_to_end():
    """End-to-end int8 continuous batching through the paged kernel: full
    deterministic generations on an int8 pool.

    Deliberately NOT a token-prefix comparison against the fp batcher:
    int8-KV rounding shifts logits at the ~1e-2 level, so any near-tie in
    a tiny random model flips a token and the flip point moves with every
    benign change to fp32 reduction order (it did, twice).  Numeric
    closeness of the int8 cache is asserted with real tolerances at the
    logit level in test_quant.test_int8_kv_cache_decode_close_to_fp; this
    test owns the serving plumbing."""
    from jax_llama_tpu.serving import ContinuousBatcher

    kw = dict(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=128,
    )
    params = init_params(jax.random.PRNGKey(0), get_config("tiny", **kw))
    rng = np.random.RandomState(6)
    prompts = [list(rng.randint(1, 128, n)) for n in (5, 19, 40)]

    def run(**cfg_kw):
        cb = ContinuousBatcher(
            params, get_config("tiny", **kw, **cfg_kw),
            n_slots=2, max_len=128, block_size=16,
        )
        # block_size 16 (% 8 == 0) routes the decode dispatch (the
        # fused chunk program; _paged_decode_step body at K=1) through
        # the Pallas kernel (kernel-vs-gathered equivalence is tested
        # above).
        rids = [cb.submit(p, max_new_tokens=10) for p in prompts]
        res = cb.run_to_completion()
        return [res[r] for r in rids]

    got = run(kv_cache_dtype="int8")
    assert all(len(g) == 10 for g in got)
    assert all(0 <= t < 128 for g in got for t in g)
    # Deterministic: the same int8 pool emits the same tokens.
    assert run(kv_cache_dtype="int8") == got


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_batcher_on_tensor_data_mesh_matches_unsharded():
    """Continuous batching on a data x tensor mesh runs the paged kernel
    per-shard via shard_map (KV heads over tensor, rows over data) and
    must reproduce the unsharded batcher's greedy output."""
    from jax_llama_tpu.parallel import make_mesh, shard_params
    from jax_llama_tpu.serving import ContinuousBatcher

    config = get_config(
        "tiny", vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=128,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(1, 128, n)) for n in (6, 23, 41)]

    def run(mesh, p):
        cb = ContinuousBatcher(
            p, config, n_slots=2, max_len=128, block_size=16, mesh=mesh,
        )
        rids = [cb.submit(x, max_new_tokens=8) for x in prompts]
        res = cb.run_to_completion()
        return [res[r] for r in rids]

    want = run(None, params)
    mesh = make_mesh(data=2, fsdp=2, tensor=2)
    got = run(mesh, shard_params(params, mesh, config))
    assert got == want


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_use_pallas_kernel_toggle_token_identical():
    """The explicit gathered-view toggle (bench's A/B knob) must not
    change tokens: kernel and gathered paths at IDENTICAL block size and
    pool geometry agree exactly (fp32 CPU), for plain and speculative
    batching."""
    from jax_llama_tpu.serving import ContinuousBatcher

    kw = dict(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=128,
    )
    config = get_config("tiny", **kw)
    params = init_params(jax.random.PRNGKey(0), config)
    rng = np.random.RandomState(9)
    prompts = [list(rng.randint(1, 128, n)) for n in (7, 23)]

    def run(use_kernel, spec):
        extra = (
            dict(draft_params=params, draft_config=config, n_draft=2)
            if spec else {}
        )
        cb = ContinuousBatcher(
            params, config, n_slots=2, max_len=128, block_size=16,
            use_pallas_kernel=use_kernel, **extra,
        )
        rids = [cb.submit(p, max_new_tokens=8) for p in prompts]
        res = cb.run_to_completion()
        return [res[r] for r in rids]

    for spec in (False, True):
        assert run(True, spec) == run(False, spec), f"spec={spec}"


def test_paged_pool_write_matches_scatter_drop_semantics():
    """paged_pool_write (the DUS chain that replaced the batched scatter
    to kill XLA:TPU's full-pool layout copies) must match
    ``plane.at[..., blk, off].set(upd, mode="drop")`` exactly — including
    dropped sentinel coordinates — on all three plane ranks."""
    from jax_llama_tpu.models.llama import paged_pool_write

    rng = np.random.RandomState(0)
    L, KVH, NB, BLK, d = 3, 2, 5, 8, 16
    B, T = 4, 2
    # DISTINCT live (blk, off) pairs: with duplicate targets the scatter
    # reference's write order is unspecified while the DUS chain is
    # last-write-wins, so equality would hinge on the seed.  (Callers
    # never produce duplicate live coordinates: paged_write_indices maps
    # each (row, token) to its own slot.)
    flat = rng.choice(NB * BLK, size=B * T, replace=False)
    blk = jnp.asarray(flat // BLK, jnp.int32).reshape(B, T)
    off = jnp.asarray(flat % BLK, jnp.int32).reshape(B, T)
    # Row 2 entirely dead; one more dead (row, token) pair.
    blk = blk.at[2].set(NB).at[0, 1].set(NB)

    plane5 = jnp.asarray(rng.randn(L, KVH, NB, BLK, d), jnp.float32)
    upd5 = jnp.asarray(rng.randn(L, KVH, B, T, d), jnp.float32)
    want5 = plane5.at[:, :, blk, off].set(upd5, mode="drop")
    got5 = paged_pool_write(plane5, upd5, blk, off)
    assert np.array_equal(np.asarray(got5), np.asarray(want5))

    plane4 = jnp.asarray(rng.randn(L, KVH, NB, BLK), jnp.float32)
    upd4 = jnp.asarray(rng.randn(L, KVH, B, T), jnp.float32)
    want4 = plane4.at[:, :, blk, off].set(upd4, mode="drop")
    got4 = paged_pool_write(plane4, upd4, blk, off)
    assert np.array_equal(np.asarray(got4), np.asarray(want4))

    plane2 = jnp.asarray(rng.randint(-5, 99, (NB, BLK)), jnp.int32)
    upd2 = jnp.asarray(rng.randint(100, 200, (B, T)), jnp.int32)
    want2 = plane2.at[blk, off].set(upd2, mode="drop")
    got2 = paged_pool_write(plane2, upd2, blk, off)
    assert np.array_equal(np.asarray(got2), np.asarray(want2))


def test_paged_pool_write_scatter_fallback_above_unroll_bound():
    """Past _POOL_WRITE_UNROLL_MAX (row, token) pairs the write switches
    to the batched scatter (op count of the DUS chain grows linearly);
    both paths must agree bit-for-bit, dead sentinels included."""
    from jax_llama_tpu.models.llama import (
        _POOL_WRITE_UNROLL_MAX, paged_pool_write,
    )

    rng = np.random.RandomState(1)
    NB, BLK = 64, 16
    B, T = _POOL_WRITE_UNROLL_MAX + 8, 1  # just past the bound
    assert B * T <= NB * BLK
    flat = rng.choice(NB * BLK, size=B * T, replace=False)
    blk = jnp.asarray(flat // BLK, jnp.int32).reshape(B, T)
    off = jnp.asarray(flat % BLK, jnp.int32).reshape(B, T)
    blk = blk.at[3].set(NB)  # dead row

    plane2 = jnp.asarray(rng.randint(-5, 99, (NB, BLK)), jnp.int32)
    upd2 = jnp.asarray(rng.randint(100, 200, (B, T)), jnp.int32)
    want2 = plane2.at[blk, off].set(upd2, mode="drop")
    got2 = paged_pool_write(plane2, upd2, blk, off)
    assert np.array_equal(np.asarray(got2), np.asarray(want2))

    L, KVH, d = 2, 2, 8
    plane5 = jnp.asarray(rng.randn(L, KVH, NB, BLK, d), jnp.float32)
    upd5 = jnp.asarray(rng.randn(L, KVH, B, T, d), jnp.float32)
    want5 = plane5.at[:, :, blk, off].set(upd5, mode="drop")
    got5 = paged_pool_write(plane5, upd5, blk, off)
    assert np.array_equal(np.asarray(got5), np.asarray(want5))
