"""ReplicaRouter: routed N-replica serving must be protocol- and
token-identical to a single server, with health-driven re-routing,
policy behavior, the aggregate observability surface, and the
disaggregation handoff counter."""

import json
import urllib.request

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.router import ReplicaRouter, handoff_prefix
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

pytestmark = pytest.mark.mesh_serving

CFG = dict(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32",
    param_dtype="float32",
)


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _post(url, payload, path="/generate", timeout=300):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode()


def _mk_server(model, tok, **kw):
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        stop_tokens=tuple(tok.stop_tokens),
    )
    return LLMServer(cb, tokenizer=tok, **kw)


@pytest.fixture(scope="module")
def fleet(model):
    """Two started replicas + a least-loaded router, shared by the
    read-only tests (server startup/teardown is ~2 s a pair and tier-1
    has no headroom); tests that mutate fleet health (drain) build
    their own."""
    tok = ByteTokenizer()
    servers = [
        _mk_server(model, tok, replica_id=i).start() for i in range(2)
    ]
    router = ReplicaRouter(servers, policy="least-loaded").start()
    try:
        yield router, servers, tok
    finally:
        router.stop()
        for s in servers:
            s.stop()


def _oracle(model, tok, prompts, max_new=8, seeds=None):
    params, config = model
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        stop_tokens=tuple(tok.stop_tokens),
    )
    rids = [
        cb.submit(
            tok.encode(p, bos=True),
            max_new_tokens=max_new,
            **({"seed": seeds[i]} if seeds else {}),
        )
        for i, p in enumerate(prompts)
    ]
    done = cb.run_to_completion()
    return [done[r] for r in rids]


def test_routed_2_replicas_token_identical(model, fleet):
    """ACCEPTANCE PIN: 2-replica routed serving ≡ 1-replica,
    token-identical per request — blocking and streaming."""
    router, servers, tok = fleet
    prompts = ["hello tpu", "paged kv", "radix tree"]
    want = _oracle(model, tok, prompts)
    replicas_seen = set()
    for i, p in enumerate(prompts):
        st, body, hdrs = _post(
            router.address, {"text": p, "max_new_tokens": 8}
        )
        assert st == 200
        assert body["tokens"] == want[i], p
        replicas_seen.add(hdrs.get("X-Replica-Id"))
    # least-loaded on idle replicas alternates — both replicas served.
    assert len(replicas_seen) == 2
    # Streaming through the router: same tokens, line-by-line NDJSON.
    req = urllib.request.Request(
        router.address + "/generate",
        data=json.dumps(
            {"text": prompts[0], "max_new_tokens": 8, "stream": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        assert r.status == 200
        lines = [json.loads(ln) for ln in r.read().splitlines() if ln]
    toks = [ln["token"] for ln in lines if "token" in ln]
    assert toks == want[0]
    assert any(ln.get("done") for ln in lines)


def test_unhealthy_replica_drains_and_reroutes(model):
    """A draining replica (ok=false on /healthz) stops receiving new
    requests (own fleet — draining the shared one would poison the
    module's other tests)."""
    tok = ByteTokenizer()
    servers = [
        _mk_server(model, tok, replica_id=i).start() for i in range(2)
    ]
    router = ReplicaRouter(
        servers, policy="least-loaded", health_interval_s=0,
    ).start()
    try:
        want = _oracle(model, tok, ["hello tpu"])[0]
        servers[0].begin_drain(timeout_s=60.0)
        router.check_health_now()
        h = router.health()
        assert [r["healthy"] for r in h["replicas"]] == [False, True]
        assert h["ok"]
        for _ in range(2):
            st, body, hdrs = _post(
                router.address,
                {"text": "hello tpu", "max_new_tokens": 8},
            )
            assert st == 200 and body["tokens"] == want
            assert hdrs.get("X-Replica-Id") == "1"
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_affinity_pins_sessions(model, fleet):
    """Affinity policy: the same session (prompt prefix) lands on the
    same replica; distinct sessions spread by load.  Rides a SECOND
    router over the shared fleet's replicas (routers are independent
    front-ends; reusing the started servers keeps this in tier-1's
    budget)."""
    _, servers, tok = fleet
    router = ReplicaRouter(servers, policy="affinity").start()
    try:
        seen = []
        for _ in range(3):
            _, _, hdrs = _post(
                router.address,
                {"text": "session one says hi", "max_new_tokens": 4},
            )
            seen.append(hdrs.get("X-Replica-Id"))
        assert len(set(seen)) == 1  # pinned
        _, _, hdrs2 = _post(
            router.address,
            {"text": "a different session", "max_new_tokens": 4},
        )
        # New session fell back to least-loaded -> the OTHER replica.
        assert hdrs2.get("X-Replica-Id") != seen[0]
        assert router.health()["affinity_sessions"] == 2
    finally:
        router.stop()  # fleet servers stay up for the module


def test_router_observability_surface(model, fleet):
    """Aggregate /healthz (replicas section), /metrics (labeled
    per-replica series), /debug passthrough with the routing decision
    on the request timeline, and replica-side serve-mesh gauges."""
    router, servers, tok = fleet
    st, body, hdrs = _post(
        router.address, {"text": "hello tpu", "max_new_tokens": 4}
    )
    assert st == 200
    rep = hdrs["X-Replica-Id"]
    h = router.health()
    assert h["ok"] and h["policy"] == "least-loaded"
    assert [r["index"] for r in h["replicas"]] == [0, 1]
    assert all(
        r["replica"]["serve_mesh"]["devices"] >= 1
        for r in h["replicas"] if r["replica"]
    )
    st, text = _get(router.address, "/metrics")
    assert st == 200
    assert "llm_router_replicas 2" in text
    assert 'llm_router_replica_healthy{replica="0"} 1' in text
    assert 'llm_router_routed_requests_total{policy="least-loaded"}' \
        in text
    # Replica-side: mesh-shape gauges + replica_id in ITS /metrics.
    st, rtext = _get(servers[int(rep)].address, "/metrics")
    assert "llm_serve_mesh_tensor 1" in rtext
    assert f"llm_replica_id {rep}" in rtext
    # /debug passthrough resolves the timeline on whichever replica
    # served it, and the timeline records the routing decision.
    st, tl = _get(
        router.address, "/debug/requests/" + body["request_id"]
    )
    assert st == 200
    tl = json.loads(tl)
    assert tl["route"] == f"replica-{rep}/least-loaded"
    assert tl["replica"] == int(rep)
    # Replica /healthz carries its replica section.
    st, rh = _get(servers[0].address, "/healthz")
    assert json.loads(rh)["replica"]["id"] == 0


def test_handoff_counter_via_router(model, fleet):
    """handoff_prefix wires the existing export/import path and the
    router counts it."""
    router, servers, tok = fleet
    params, config = model
    prompt = list(np.random.RandomState(3).randint(1, 128, 40))

    def mk():
        return ContinuousBatcher(
            params, config, n_slots=2, max_len=64, block_size=16,
        )

    src, dst = mk(), mk()
    r = src.submit(prompt, max_new_tokens=4)
    src.run_to_completion()[r]
    n = handoff_prefix(src, dst, prompt, router=router)
    assert n > 0
    # The destination now matches the chain as a plain prefix hit
    # (full token-identity of the subsequent serve is pinned by
    # test_serve_mesh.test_kv_handoff_token_identity).
    keys = dst._chain_keys(prompt, dst.block_size)
    assert len(dst._match_prefix(keys).blocks) == n
    assert router.health()["kv_handoffs_total"] == 1
    assert "llm_router_kv_handoffs_total 1" in router.metrics_text()


def test_fleet_debug_requests_aggregation_and_routing_record(
    model, fleet,
):
    """/debug/requests on the router aggregates ALL healthy replicas
    (entries tagged with their replica id — not first-to-answer), and
    /debug/requests/<id> resolves through the routing record the relay
    filled from each reply's X-Request-Id."""
    router, servers, tok = fleet
    ids = {}
    for i, text in enumerate(["fleet dbg a", "fleet dbg b"]):
        req = urllib.request.Request(
            router.address + "/generate",
            data=json.dumps(
                {"text": text, "max_new_tokens": 4}
            ).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Request-Id": f"fleet-req-{i}",
            },
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            assert r.status == 200
            ids[f"fleet-req-{i}"] = int(r.headers["X-Replica-Id"])
    # Least-loaded on an idle pair alternates: both replicas served.
    assert set(ids.values()) == {0, 1}
    st, text = _get(router.address, "/debug/requests")
    assert st == 200
    idx = json.loads(text)
    assert sorted(idx["replicas"]) == [0, 1]
    by_id = {e["request_id"]: e for e in idx["requests"]}
    for rid, rep in ids.items():
        assert by_id[rid]["replica"] == rep, rid
    # Routed lookup: the routing record names the serving replica —
    # the OTHER replica is never asked first, so the answer cannot be
    # a first-healthy-replica guess.
    for rid, rep in ids.items():
        st, tl = _get(router.address, "/debug/requests/" + rid)
        assert st == 200
        tl = json.loads(tl)
        assert tl["replica"] == rep
        assert tl["routed_replica"] == rep


def test_fleet_merged_trace_schema_and_handoff_link(model, fleet):
    """ACCEPTANCE PIN: the router's /debug/trace is ONE loadable
    Perfetto document — router track + both replica tracks re-tagged
    to their own pids with clock-offset-normalized timestamps — whose
    router spans are causally ordered (every forward follows a route
    to the same replica) and whose handoff span links the prefix move
    by external request id (the same id both batchers' export/import
    annotations carry)."""
    router, servers, tok = fleet
    params, config = model
    for text in ("trace seed a", "trace seed b"):
        st, _, _ = _post(
            router.address, {"text": text, "max_new_tokens": 4}
        )
        assert st == 200
    # A handoff brokered through the router, linked by external id.
    prompt = list(np.random.RandomState(7).randint(1, 128, 40))

    def mk():
        return ContinuousBatcher(
            params, config, n_slots=2, max_len=64, block_size=16,
        )

    src, dst = mk(), mk()
    src.submit(prompt, max_new_tokens=4)
    src.run_to_completion()
    n = handoff_prefix(
        src, dst, prompt, router=router,
        request_id="sess-handoff-1", src=0, dst=1,
    )
    assert n > 0
    # Both batchers' rings carry the linked annotations.
    for cb, name in ((src, "prefix_export"), (dst, "prefix_import")):
        evs = [
            e for e in cb.obs.trace_json()["traceEvents"]
            if e.get("name") == name
        ]
        assert evs, name
        assert evs[-1]["args"]["request_id"] == "sess-handoff-1"
    st, text = _get(router.address, "/debug/trace")
    assert st == 200
    doc = json.loads(text)  # loadable Perfetto JSON
    assert doc["displayTimeUnit"] == "ms" and "t0_unix_s" in doc
    assert sorted(doc["replicas"]) == [0, 1]
    evs = doc["traceEvents"]
    procs = {
        e["args"]["name"] for e in evs
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert {"router", "replica-0", "replica-1"} <= procs
    # Replica tracks carry real slices, shifted into the router frame.
    slice_pids = {e["pid"] for e in evs if e.get("ph") == "X"}
    assert {0, 1, 2} <= slice_pids
    # Router spans causally ordered: every forward follows a route
    # decision to the same replica.
    router_x = [
        e for e in evs if e.get("pid") == 0 and e.get("ph") == "X"
    ]
    routes = [e for e in router_x if e["name"] == "route"]
    fwds = [e for e in router_x if e["name"] == "forward"]
    assert routes and fwds
    for f in fwds:
        assert any(
            r["ts"] <= f["ts"]
            and r["args"]["replica"] == f["args"]["replica"]
            for r in routes
        ), "forward without a preceding route decision"
    # The linked handoff span.
    hand = [e for e in router_x if e["name"] == "handoff"]
    assert hand
    assert hand[-1]["args"]["request_id"] == "sess-handoff-1"
    assert hand[-1]["args"]["blocks"] == n
    assert hand[-1]["args"]["src"] == 0
    assert hand[-1]["args"]["dst"] == 1


def test_fleet_kv_view_reports_duplicate_chains(model, fleet):
    """ACCEPTANCE PIN: GET /debug/kv/fleet on the routed 2-replica CPU
    fleet reports the fleet-wide prefix-hit ratio and NONZERO
    cross-replica duplicate-chain bytes for a deliberately shared
    prefix, and the router /metrics surface carries the fleet gauges,
    the per-replica labeled kv gauges, and the health-age staleness
    gauge qualifying them."""
    router, servers, tok = fleet
    # Publish the SAME chain on BOTH replicas: direct per-replica
    # posts (deterministic — least-loaded tie-breaks depend on what
    # earlier tests routed), then read the ROUTER's aggregated view.
    shared = "shared system prompt for chat session A"
    for s in servers:
        st, body, _ = _post(
            s.address, {"text": shared, "max_new_tokens": 4}
        )
        assert st == 200
    router.check_health_now()  # refresh last_health kv summaries
    st, text = _get(router.address, "/debug/kv/fleet")
    assert st == 200
    doc = json.loads(text)
    fl = doc["fleet"]
    assert sorted(fl["replicas_scraped"]) == [0, 1]
    # The deliberately shared prefix is HBM-resident on both replicas:
    # >= 2 duplicate chain blocks, priced in real pool bytes — the
    # number that justifies the disaggregation scheduler.
    assert fl["duplicate_chains"] >= 2
    assert fl["duplicate_kv_blocks"] >= 2
    bb = servers[0].batcher.block_bytes
    assert fl["duplicate_kv_bytes"] >= 2 * bb
    assert fl["duplicate_kv_bytes"] % bb == 0
    # Fleet-wide hit ratio aggregates per-replica token counters.
    assert 0.0 <= fl["prefix_hit_ratio"] <= 1.0
    assert fl["prompt_tokens_total"] > 0
    per = {p["replica"]: p for p in doc["replicas"]}
    assert set(per) == {0, 1}
    for p in per.values():
        assert p["summary"]["nodes"] >= 2
        assert p["hbm_bytes"] >= 2 * bb
    # Router /metrics: fleet gauges (from the cached computation),
    # per-replica labeled kv gauges, and the staleness gauge.
    text = router.metrics_text()
    assert (
        f"llm_fleet_duplicate_kv_blocks {fl['duplicate_kv_blocks']}"
        in text
    )
    assert (
        f"llm_fleet_duplicate_kv_bytes {fl['duplicate_kv_bytes']}"
        in text
    )
    assert "llm_fleet_prefix_hit_ratio" in text
    for i in (0, 1):
        assert f'llm_router_replica_kv_nodes{{replica="{i}"}}' in text
        assert (
            f'llm_router_replica_kv_digest_version{{replica="{i}"}}'
            in text
        )
        # Freshly scraped: age is present and small (never -1).
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith(f'llm_replica_health_age_s{{replica="{i}"')
        )
        assert 0.0 <= float(line.split()[-1]) < 60.0
    # The aggregate /healthz mirrors the fleet cache view.
    h = router.health()
    assert h["fleet_kv"]["duplicate_kv_bytes"] == (
        fl["duplicate_kv_bytes"]
    )


def test_affinity_stale_route_counted_on_digest_loss(model, fleet):
    """Digest freshness in the affinity policy: a pinned session whose
    replica's chain-digest loss_version changed since pin routes
    anyway, but as a COUNTED stale event (re-pinned at the observed
    version so one loss counts once)."""
    _, servers, tok = fleet
    router = ReplicaRouter(
        servers, policy="affinity", health_interval_s=0,
    ).start()
    try:
        router.check_health_now()
        st, _, hdrs = _post(
            router.address,
            {"text": "sticky session for staleness", "max_new_tokens": 4},
        )
        assert st == 200
        rep = int(hdrs["X-Replica-Id"])
        assert router.affinity_stale_routes_total == 0
        # Simulate the pinned replica losing chains: bump the scraped
        # loss_version out from under the pin (the real path would be
        # an eviction/demotion between health scrapes).
        with router._lock:
            r = router._replicas[rep]
            kv = dict(r.last_health.get("kv") or {})
            dig = dict(kv.get("digest") or {})
            dig["loss_version"] = (dig.get("loss_version") or 0) + 7
            kv["digest"] = dig
            r.last_health = dict(r.last_health, kv=kv)
        st, _, hdrs = _post(
            router.address,
            {"text": "sticky session for staleness", "max_new_tokens": 4},
        )
        assert st == 200
        assert int(hdrs["X-Replica-Id"]) == rep  # still routed there
        assert router.affinity_stale_routes_total == 1
        assert (
            "llm_router_affinity_stale_routes_total 1"
            in router.metrics_text()
        )
        # Re-pinned at the observed version: the SAME loss event does
        # not count again on the next turn.
        st, _, _ = _post(
            router.address,
            {"text": "sticky session for staleness", "max_new_tokens": 4},
        )
        assert st == 200
        assert router.affinity_stale_routes_total == 1
        # A session pinned BEFORE the replica's first digest scrape
        # (None baseline) backfills at the first observed version —
        # staleness detection works for its later turns (review fix:
        # a permanent None would disable it for the session's life).
        with router._lock:
            router._affinity[b"t:pre-scrape session pin"] = [rep, None]
        st, _, _ = _post(
            router.address,
            {"text": "pre-scrape session pin", "max_new_tokens": 4},
        )
        assert st == 200
        with router._lock:
            backfilled = router._affinity[b"t:pre-scrape session pin"][1]
        assert backfilled is not None  # baseline adopted
        with router._lock:
            r = router._replicas[rep]
            kv = dict(r.last_health.get("kv") or {})
            dig = dict(kv.get("digest") or {})
            dig["loss_version"] = (dig.get("loss_version") or 0) + 3
            kv["digest"] = dig
            r.last_health = dict(r.last_health, kv=kv)
        st, _, _ = _post(
            router.address,
            {"text": "pre-scrape session pin", "max_new_tokens": 4},
        )
        assert st == 200
        assert router.affinity_stale_routes_total == 2
    finally:
        router.stop()  # fleet servers stay up for the module


def test_router_input_validation(model, fleet):
    import urllib.error

    router, servers, tok = fleet
    with pytest.raises(ValueError):
        ReplicaRouter([], policy="least-loaded")
    with pytest.raises(ValueError):
        ReplicaRouter(servers, policy="round-robin")
    with pytest.raises(urllib.error.HTTPError):
        _get(router.address, "/nope")
