"""Test environment: force CPU with 8 virtual devices BEFORE jax import.

Mirrors the survey's test-plan recommendation (SURVEY.md §4): DP/TP/FSDP
paths must be testable without TPU hardware via
``--xla_force_host_platform_device_count``.
"""

import os

# NOTE: this image's sitecustomize registers the axon TPU backend and forces
# JAX_PLATFORMS=axon before conftest runs, so a plain env var is not enough —
# jax.config.update after import is authoritative.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
