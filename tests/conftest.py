"""Test environment: force CPU with 8 virtual devices BEFORE jax import.

Mirrors the survey's test-plan recommendation (SURVEY.md §4): DP/TP/FSDP
paths must be testable without TPU hardware via
``--xla_force_host_platform_device_count``.

Exception: ``pytest -m tpu`` (exactly that mark expression) keeps the
real TPU backend so tests/test_tpu_compiled.py can compile the Pallas
kernels on the chip; those tests skip themselves on any other backend.
"""

import os
import sys

# NOTE: this image's sitecustomize registers the axon TPU backend and forces
# JAX_PLATFORMS=axon before conftest runs, so a plain env var is not enough —
# jax.config.update after import is authoritative.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax


def _tpu_marker_run() -> bool:
    # The platform must be pinned before any test module touches a device,
    # which is earlier than pytest_configure reliably exposes options
    # across plugin orderings — parse argv directly.
    argv = sys.argv
    for i, a in enumerate(argv):
        if a == "-m" and i + 1 < len(argv):
            return argv[i + 1].strip() == "tpu"
        if a.startswith("-m="):
            return a[3:].strip() == "tpu"
    return False


if not _tpu_marker_run():
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: compiled-on-chip kernel regression tests (run: pytest -m tpu "
        "on a TPU host; forced-CPU otherwise and the tests self-skip)",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / crash-recovery / watchdog tests "
        "(CPU-safe and part of the default tier-1 run; select just them "
        "with pytest -m faults)",
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 run "
        "(pytest -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "chaos: full fault-matrix smoke drills (make chaos / "
        "pytest -m 'chaos or faults'); the heavy ones are also marked "
        "slow so tier-1 keeps its time headroom",
    )


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_compile_cache():
    """Clear JAX's compiled-executable caches after each test module.

    Running the FULL suite in one process accumulates every module's
    compiled CPU executables; past ~200 tests the XLA:CPU compiler was
    observed to segfault mid-compile (reproduced twice at ~80% of the
    full run, with >100GB RAM free; any module subset passes in
    isolation).  Modules share almost no jit cache entries (each uses its
    own tiny configs), so per-module clearing costs little and keeps the
    process state bounded.

    Set JLT_NO_CACHE_CLEAR=1 to disable the workaround — the repro
    switch for chasing the underlying crash (run the full suite with
    ``-p faulthandler`` and a core-dump ulimit to capture where the
    XLA:CPU compiler dies).
    """
    yield
    if not os.environ.get("JLT_NO_CACHE_CLEAR"):
        jax.clear_caches()
