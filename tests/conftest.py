"""Test environment: force CPU with 8 virtual devices BEFORE jax import.

Mirrors the survey's test-plan recommendation (SURVEY.md §4): DP/TP/FSDP
paths must be testable without TPU hardware via
``--xla_force_host_platform_device_count``.

Exception: ``pytest -m tpu`` (exactly that mark expression) keeps the
real TPU backend so tests/test_tpu_compiled.py can compile the Pallas
kernels on the chip; those tests skip themselves on any other backend.
"""

import os
import sys

# NOTE: this image's sitecustomize registers the axon TPU backend and forces
# JAX_PLATFORMS=axon before conftest runs, so a plain env var is not enough —
# jax.config.update after import is authoritative.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax


def _tpu_marker_run() -> bool:
    # The platform must be pinned before any test module touches a device,
    # which is earlier than pytest_configure reliably exposes options
    # across plugin orderings — parse argv directly.
    argv = sys.argv
    for i, a in enumerate(argv):
        if a == "-m" and i + 1 < len(argv):
            return argv[i + 1].strip() == "tpu"
        if a.startswith("-m="):
            return a[3:].strip() == "tpu"
    return False


if not _tpu_marker_run():
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: compiled-on-chip kernel regression tests (run: pytest -m tpu "
        "on a TPU host; forced-CPU otherwise and the tests self-skip)",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / crash-recovery / watchdog tests "
        "(CPU-safe and part of the default tier-1 run; select just them "
        "with pytest -m faults)",
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 run "
        "(pytest -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "chaos: full fault-matrix smoke drills (make chaos / "
        "pytest -m 'chaos or faults'); the heavy ones are also marked "
        "slow so tier-1 keeps its time headroom",
    )
    config.addinivalue_line(
        "markers",
        "kvcache: KV-capacity subsystem tests (radix prefix index + "
        "host-DRAM block tier; CPU-safe and part of the default "
        "tier-1 run — select just them with pytest -m kvcache)",
    )
    config.addinivalue_line(
        "markers",
        "obs: observability-layer tests (request timelines, dispatch "
        "spans, latency histograms, SLO accounting, /metrics "
        "exposition, /debug endpoints; CPU-safe and part of the "
        "default tier-1 run — select just them with pytest -m obs "
        "or make obs)",
    )
    config.addinivalue_line(
        "markers",
        "overload: overload-control tests (priority classes, "
        "deadline-aware admission, brownout ladder, open-loop flood "
        "drills — overload.py; CPU-safe, the core set runs in tier-1 "
        "and the heavy acceptance drill is also marked slow — select "
        "with pytest -m overload or make overload)",
    )
    config.addinivalue_line(
        "markers",
        "analysis: invariant-auditor tests (host-boundary lint, "
        "lowering contracts, lock discipline — jax_llama_tpu.analysis; "
        "the static package-cleanliness gates run in tier-1, the "
        "abstract-trace layer is also marked slow — select just them "
        "with pytest -m analysis or make lint-invariants)",
    )
    config.addinivalue_line(
        "markers",
        "mesh_serving: scale-out serving tests (mesh-sharded chunk "
        "programs on the forced 8-device CPU host mesh, sharded KV "
        "pool placement, the ReplicaRouter + disaggregation handoff "
        "— parallel/serve_mesh.py + router.py; the core parity pins "
        "run in tier-1, the broad matrices are also marked slow — "
        "select with pytest -m mesh_serving or make mesh-serve)",
    )


# ---------------------------------------------------------------------------
# Environment-skew detection (the PR-1 version-skew-shim discipline:
# detect the environment, don't pin it).  This image's jax/jaxlib
# predates two behaviors the code and tests are written against; the
# affected tests skip/xfail WITH the detected evidence instead of
# failing tier-1, and keep failing loudly on any other error.
# ---------------------------------------------------------------------------

# The older XLA SPMD partitioner rejects ``lax.axis_index`` inside a
# PARTIALLY-manual shard_map (manual stage axis, auto tensor/data axes
# remaining): it lowers to a bare PartitionId instruction, which SPMD
# partitioning refuses as ambiguous.  Current jax/XLA handles it; the
# pipeline stack legitimately uses exactly that construct.  Verified
# pre-existing at the PR-3 seed via git-stash A/B (see CHANGES.md).
_XLA_PARTITION_ID_SKEW_TEXT = (
    "PartitionId instruction is not supported for SPMD partitioning"
)


def skip_if_xla_partition_id_skew(exc: BaseException) -> None:
    """Skip (with the detected evidence) when ``exc`` is the known
    jaxlib PartitionId/SPMD version skew; re-raise anything else."""
    if _XLA_PARTITION_ID_SKEW_TEXT in str(exc):
        pytest.skip(
            "environment jaxlib skew (detected from the raised error): "
            f"'{_XLA_PARTITION_ID_SKEW_TEXT}' — this build cannot lower "
            "lax.axis_index inside a partially-manual shard_map (the "
            "pipeline-over-mixed-mesh construct); fine on current "
            "jax/XLA, pre-existing at the seed of this image"
        )
    raise exc


def mesh_guarded(fn, *args, **kwargs):
    """Run a mesh-dispatching callable, converting THE known jaxlib
    PartitionId/SPMD skew into a clean skip (every other exception
    propagates) — the serve-mesh tests' wrapper around their first
    sharded dispatch, extending ``skip_if_xla_partition_id_skew`` to
    call sites that do not want a try/except at every dispatch."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:  # noqa: BLE001 - skew detection re-raises
        skip_if_xla_partition_id_skew(e)


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """The multi-device CPU fleet for mesh_serving tests: conftest
    already forces ``--xla_force_host_platform_device_count=8`` before
    jax import (top of this file), so this fixture only asserts the
    environment delivered them (a stray XLA_FLAGS override would
    otherwise fail every mesh test with an opaque mesh-size error)."""
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip(
            f"need 8 forced host devices for serving-mesh tests, "
            f"have {len(devs)} (XLA_FLAGS overridden?)"
        )
    return devs


def xfail_if_remat_ulp_skew(a: np.ndarray, b: np.ndarray, label) -> bool:
    """The remat bit-identity check's skew valve: this image's XLA:CPU
    fuses the rematerialized backward slightly differently, wobbling
    gradient entries at rounding scale (large entries by ~1 ulp,
    near-zero entries by up to ~1e-3 relative; verified
    identical-failure at the PR-3 seed).  A rounding-scale diff is the
    DETECTED skew — assert it really is that small, then report xfail;
    a substantive diff (a real remat math break changes what gets
    recomputed, i.e. whole terms) still fails the allclose hard.
    Returns True when the skew was detected (caller xfails at the end,
    after checking every pair)."""
    if np.array_equal(a, b):
        return False
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-6,
                               err_msg=str(label))
    return True


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_compile_cache():
    """Clear JAX's compiled-executable caches after each test module.

    Running the FULL suite in one process accumulates every module's
    compiled CPU executables; past ~200 tests the XLA:CPU compiler was
    observed to segfault mid-compile (reproduced twice at ~80% of the
    full run, with >100GB RAM free; any module subset passes in
    isolation).  Modules share almost no jit cache entries (each uses its
    own tiny configs), so per-module clearing costs little and keeps the
    process state bounded.

    Set JLT_NO_CACHE_CLEAR=1 to disable the workaround — the repro
    switch for chasing the underlying crash (run the full suite with
    ``-p faulthandler`` and a core-dump ulimit to capture where the
    XLA:CPU compiler dies).
    """
    yield
    if not os.environ.get("JLT_NO_CACHE_CLEAR"):
        jax.clear_caches()
