"""Downloader checksum logic (parity: reference download.sh's md5sum -c
loop — the network fetch itself is not unit-testable, the verification
is)."""

from pathlib import Path

from jax_llama_tpu.download import (
    N_SHARDS,
    md5_file,
    parse_checklist,
    verify_checklist,
)


def test_parse_checklist_md5sum_format():
    text = "0123abc  consolidated.00.pth\ndeadbeef *params.json\n\n"
    assert parse_checklist(text) == [
        ("0123abc", "consolidated.00.pth"),
        ("deadbeef", "params.json"),
    ]


def test_verify_checklist_roundtrip(tmp_path: Path):
    f = tmp_path / "params.json"
    f.write_bytes(b'{"dim": 4096}')
    (tmp_path / "checklist.chk").write_text(f"{md5_file(f)}  params.json\n")
    assert verify_checklist(tmp_path)
    f.write_bytes(b"corrupted")
    assert not verify_checklist(tmp_path)


def test_verify_checklist_missing_file(tmp_path: Path):
    (tmp_path / "checklist.chk").write_text("00ff  missing.pth\n")
    assert not verify_checklist(tmp_path)
    assert not verify_checklist(tmp_path / "nonexistent")


def test_shard_counts_cover_published_sizes():
    # README.md:44-53 of the reference: MP degrees per size; shard count
    # equals the fairscale MP degree of the published checkpoints.
    assert N_SHARDS["7B"] == 1 and N_SHARDS["13B"] == 2
    assert N_SHARDS["65B"] == 8 and N_SHARDS["70B"] == 8
