"""Downloader checksum logic (parity: reference download.sh's md5sum -c
loop — the network fetch itself is not unit-testable, the verification
is)."""

from pathlib import Path

import pytest

from jax_llama_tpu.download import (
    N_SHARDS,
    md5_file,
    parse_checklist,
    verify_checklist,
)


def test_parse_checklist_md5sum_format():
    text = "0123abc  consolidated.00.pth\ndeadbeef *params.json\n\n"
    assert parse_checklist(text) == [
        ("0123abc", "consolidated.00.pth"),
        ("deadbeef", "params.json"),
    ]


def test_verify_checklist_roundtrip(tmp_path: Path):
    f = tmp_path / "params.json"
    f.write_bytes(b'{"dim": 4096}')
    (tmp_path / "checklist.chk").write_text(f"{md5_file(f)}  params.json\n")
    assert verify_checklist(tmp_path)
    f.write_bytes(b"corrupted")
    assert not verify_checklist(tmp_path)


def test_verify_checklist_missing_file(tmp_path: Path):
    (tmp_path / "checklist.chk").write_text("00ff  missing.pth\n")
    assert not verify_checklist(tmp_path)
    assert not verify_checklist(tmp_path / "nonexistent")


def test_shard_counts_cover_published_sizes():
    # README.md:44-53 of the reference: MP degrees per size; shard count
    # equals the fairscale MP degree of the published checkpoints.
    assert N_SHARDS["7B"] == 1 and N_SHARDS["13B"] == 2
    assert N_SHARDS["65B"] == 8 and N_SHARDS["70B"] == 8


def test_download_resumes_verified_shards(tmp_path: Path, monkeypatch):
    """Interrupted model download re-fetches only missing/corrupt shards."""
    import jax_llama_tpu.download as dl

    d = tmp_path / "13B"
    d.mkdir()
    good = d / "consolidated.00.pth"
    good.write_bytes(b"shard zero")
    params = d / "params.json"
    params.write_bytes(b"{}")
    # checklist covers both shards + params; shard 1 is missing (interrupt)
    (d / "checklist.chk").write_text(
        f"{md5_file(good)}  consolidated.00.pth\n"
        f"{md5_file(params)}  params.json\n"
        "0123456789abcdef0123456789abcdef  consolidated.01.pth\n"
    )
    (tmp_path / "tokenizer.model").write_bytes(b"tok")
    (tmp_path / "tokenizer_checklist.chk").write_text(
        f"{md5_file(tmp_path / 'tokenizer.model')}  tokenizer.model\n"
    )

    fetched = []

    def fake_fetch(url, dest):
        fetched.append(dest.name)
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_bytes(b"shard one")

    monkeypatch.setattr(dl, "_fetch", fake_fetch)
    # final verify fails (fake shard 1 has wrong digest) -> SystemExit; the
    # point of the test is which files were fetched before that.
    try:
        dl.download("https://host/*?sig", ["13B"], tmp_path)
    except SystemExit:
        pass
    assert fetched == ["consolidated.01.pth"]


class _FakeResponse:
    """Minimal context-managed urlopen response."""

    def __init__(self, payload: bytes):
        import io

        self._buf = io.BytesIO(payload)

    def __enter__(self):
        return self._buf

    def __exit__(self, *exc):
        return False


def test_fetch_retries_transient_then_succeeds(tmp_path: Path):
    """A flaky opener (URLError, then HTTP 503) is retried with
    exponential backoff + jitter and a socket timeout on every attempt;
    the third attempt lands atomically (no .part left behind)."""
    import urllib.error

    import jax_llama_tpu.download as dl

    calls, sleeps = [], []

    def opener(url, timeout):
        calls.append(timeout)
        if len(calls) == 1:
            raise urllib.error.URLError("connection reset")
        if len(calls) == 2:
            raise urllib.error.HTTPError(url, 503, "unavailable", None, None)
        return _FakeResponse(b"payload")

    dest = tmp_path / "f.bin"
    dl._fetch(
        "https://host/f.bin?sig", dest,
        opener=opener, sleep=sleeps.append, jitter=lambda: 0.5,
    )
    assert dest.read_bytes() == b"payload"
    assert not (tmp_path / "f.bin.part").exists()
    assert calls == [dl.FETCH_TIMEOUT_S] * 3   # timeout on every attempt
    # base * 2^attempt * (0.5 + jitter): bounded exponential backoff
    assert sleeps == [dl.FETCH_BACKOFF_BASE_S * 1.0,
                      dl.FETCH_BACKOFF_BASE_S * 2.0]


def test_fetch_4xx_fails_immediately(tmp_path: Path):
    """Client errors (expired presigned URL) are not transient: no
    retry, no sleep."""
    import urllib.error

    import jax_llama_tpu.download as dl

    calls, sleeps = [], []

    def opener(url, timeout):
        calls.append(url)
        raise urllib.error.HTTPError(url, 403, "forbidden", None, None)

    with pytest.raises(urllib.error.HTTPError):
        dl._fetch(
            "https://host/x?sig", tmp_path / "x",
            opener=opener, sleep=sleeps.append,
        )
    assert len(calls) == 1 and sleeps == []


def test_fetch_retry_budget_is_bounded(tmp_path: Path):
    """A persistently failing fetch raises after 1 + retries attempts."""
    import urllib.error

    import jax_llama_tpu.download as dl

    calls, sleeps = [], []

    def opener(url, timeout):
        calls.append(url)
        raise urllib.error.URLError("no route to host")

    with pytest.raises(urllib.error.URLError):
        dl._fetch(
            "https://host/x?sig", tmp_path / "x",
            opener=opener, retries=2, sleep=sleeps.append,
            jitter=lambda: 0.0,
        )
    assert len(calls) == 3
    assert sleeps == [0.5, 1.0]   # base * 2^attempt * 0.5 (no jitter)


def test_initialize_single_host_is_noop(monkeypatch):
    """One worker hostname (single-host TPU VM) must not bring up the
    coordination service; >1 workers must."""
    import jax_llama_tpu.parallel.distributed as dist

    calls = []
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(
        dist.jax.distributed, "initialize",
        lambda **kw: calls.append(kw),
    )
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0")
    dist.initialize()
    assert calls == []

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    dist.initialize()
    assert len(calls) == 1
