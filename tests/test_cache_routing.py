"""Globally cache-aware routing (PR 14): the router-side global radix
index (incremental digest sync), the cache-aware policy (deepest-prefix
routing, occupancy spill, stale-digest degradation), the handoff
scheduler (bounded, deduplicated, cancellation-safe chain migration
with demote-after-export), and prefill/decode disaggregation roles —
all token-identical to the single-replica oracle."""

import json
import urllib.request

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.kvcache import KvDigest
from jax_llama_tpu.router import (
    ReplicaRouter, RouterRadixIndex, chain_keys,
)
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

pytestmark = pytest.mark.mesh_serving

CFG = dict(
    vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32",
    param_dtype="float32",
)

# Long enough for 2 chain-key blocks at block_size=16 (41 tokens with
# the ByteTokenizer bos) while keeping every prompt + max_new inside
# the max_len=64 geometry (the SAME geometry test_router.py uses, so
# the two files share one set of jitted-program compiles in tier-1).
SESSION = "the quick brown fox jumps over the lazy d"
REVISIT = SESSION + " next!"
OTHER = "a completely different conversation starts h"


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def _mk_batcher(model, tok, **kw):
    params, config = model
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 64)
    return ContinuousBatcher(
        params, config, stop_tokens=tuple(tok.stop_tokens), **kw
    )


def _serve_direct(cb, tok, prompts, max_new=6, seeds=None):
    rids = [
        cb.submit(
            tok.encode(p, bos=True), max_new_tokens=max_new,
            **({"seed": seeds[i]} if seeds else {}),
        )
        for i, p in enumerate(prompts)
    ]
    done = cb.run_to_completion()
    return [done[r] for r in rids]


def _post(url, payload, path="/generate", timeout=300):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _stream_tokens(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate",
        data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"},
    )
    toks = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        hdrs = dict(r.headers)
        for line in r:
            obj = json.loads(line)
            if "token" in obj:
                toks.append(obj["token"])
    return toks, hdrs


# ---------------------------------------------------------------------------
# Host-only units: shared key schema, digest journal, global index
# ---------------------------------------------------------------------------

def test_chain_key_schema_shared_with_batcher():
    """router.chain_keys IS the batcher's chain-key schema (the
    delegation must never drift — the global index routes on it)."""
    toks = list(range(1, 40))
    assert chain_keys(toks, 16) == ContinuousBatcher._chain_keys(
        toks, 16
    )
    # Only blocks strictly before the last token are keyed.
    assert len(chain_keys(toks, 16)) == (len(toks) - 1) // 16
    assert chain_keys(toks[:10], 16) == []


def test_digest_journal_incremental_sync_semantics():
    d = KvDigest()
    assert d.events_since(0) == ([], 0)
    d.on_publish(b"k1", 1)
    d.on_publish(b"k2", 2)
    ev, ver = d.events_since(0)
    assert ver == d.version == 2
    assert [e["op"] for e in ev] == ["publish", "publish"]
    assert ev[0]["key"] == b"k1".hex() and ev[0]["depth"] == 1
    # Tier transitions journal with their target tier.
    d.on_demote(b"k2")
    d.on_restore(b"k2")
    d.on_remove(b"k1")
    ev, ver = d.events_since(2)
    assert [(e["op"], e["tier"]) for e in ev] == [
        ("demote", "host"), ("restore", "hbm"), ("remove", "hbm"),
    ]
    # Catching up from the current version is an empty delta.
    assert d.events_since(ver) == ([], ver)
    # A consumer from the FUTURE (rebuild reset the digest) resyncs.
    fresh = KvDigest()
    fresh.on_publish(b"k1", 1)
    assert fresh.events_since(ver) is None
    # A consumer past the bounded window resyncs.
    big = KvDigest()
    for i in range(KvDigest.JOURNAL_MAX + 10):
        big.on_publish(b"key-%d" % i, 1)
    assert big.events_since(0) is None
    got = big.events_since(big.version - 5)
    assert got is not None and len(got[0]) == 5


def test_router_radix_index_lookup_and_sync():
    idx = RouterRadixIndex()
    k = [bytes([i]).hex() * 2 for i in range(4)]
    idx.replace(
        0,
        [{"key": k[0], "depth": 1, "tier": "hbm"},
         {"key": k[1], "depth": 2, "tier": "hbm"}],
        version=5, block_bytes=1024,
    )
    idx.replace(
        1, [{"key": k[0], "depth": 1, "tier": "hbm"}],
        version=3, block_bytes=1024,
    )
    # Deepest prefix wins: replica 0 holds depth 2.
    depth, holders = idx.lookup(k[:3], {0, 1})
    assert depth == 2 and holders == [(0, "hbm")]
    # Restricted to replica 1, the depth-1 key is the best match.
    depth, holders = idx.lookup(k[:3], {1})
    assert depth == 1 and holders == [(1, "hbm")]
    # Fleet-wide miss.
    assert idx.lookup([k[3]], {0, 1}) is None
    assert idx.synced_version(0) == 5 and idx.block_bytes(0) == 1024
    # Incremental events: demote flips the tier, remove drops the key.
    idx.apply_events(
        0,
        [{"op": "demote", "key": k[1], "depth": 2, "tier": "host"},
         {"op": "remove", "key": k[0]},
         {"op": "host_evict", "key": k[1]}],  # counter-only: ignored
        version=8,
    )
    depth, holders = idx.lookup(k[:2], {0})
    assert (depth, holders) == (2, [(0, "host")])
    assert idx.lookup([k[0]], {0}) is None
    assert idx.synced_version(0) == 8
    # Optimistic handoff note: dst gains hbm, src drops to host.
    idx.note_handoff(1, 0, [k[0]])
    assert idx.lookup([k[0]], {0}) == (1, [(0, "hbm")])
    assert idx.lookup([k[0]], {1}) == (1, [(1, "host")])
    st = idx.stats()
    assert st["replicas_synced"] == 2 and st["resyncs_total"] == 2
    assert st["events_applied_total"] == 3


def test_epoch_change_forces_full_resync(monkeypatch):
    """A rebuild mints a new digest epoch; even when the rebuilt
    replica's replayed version catches up to (or passes) the synced
    one, the router must FULL-resync — version arithmetic across
    epochs is meaningless (a bogus incremental delta would keep
    phantom pre-crash keys in the index forever)."""
    router = ReplicaRouter(
        ["127.0.0.1:1"], policy="cache-aware",
        health_interval_s=0, block_size=16,
    )
    router.index.replace(0, [], version=9, epoch="epoch-A")
    asked = []

    def fake_get(rep, path, timeout=2.0):
        asked.append(path)
        return 200, {"version": 9, "nodes": [],
                     "summary": {"epoch": "epoch-B"}}

    monkeypatch.setattr(router, "_get_replica_json", fake_get)
    rep = router._replicas[0]
    # Same version (9) but a NEW epoch: same-version short-circuit
    # must not fire; the fetch must be the full walk, not ?since=9.
    router._sync_index(rep, {
        "kv": {"digest": {"version": 9, "epoch": "epoch-B"}},
    })
    assert asked == ["/debug/kv?n=1000000"]
    assert router.index.synced_epoch(0) == "epoch-B"
    # Same epoch + same version: no fetch at all.
    router._sync_index(rep, {
        "kv": {"digest": {"version": 9, "epoch": "epoch-B"}},
    })
    assert len(asked) == 1
    # Same epoch, newer version: incremental.
    router._sync_index(rep, {
        "kv": {"digest": {"version": 11, "epoch": "epoch-B"}},
    })
    # Uncapped even on the incremental form: a server-side journal
    # gap falls back to the full walk, which must not truncate.
    assert asked[-1] == "/debug/kv?since=9&n=1000000"


def test_cache_pick_spill_watermark_and_handoff_plan():
    """The pick decision table, white-box: deep hit routes to the
    holder under the watermark, spills to least-loaded past it with a
    migration plan once depth x load-gap clears the threshold; the
    scheduler's admission dedups chains and refuses out-of-process
    replicas."""
    router = ReplicaRouter(
        ["127.0.0.1:1", "127.0.0.1:2"], policy="cache-aware",
        health_interval_s=0, block_size=16,
        handoff_threshold=1.0, handoff_min_depth=1,
    )
    k = [bytes([i]).hex() * 2 for i in range(3)]
    router.index.replace(
        0, [{"key": k[0], "depth": 1, "tier": "hbm"},
            {"key": k[1], "depth": 2, "tier": "hbm"}],
        version=1, block_bytes=512,
    )
    for rep in router._replicas:
        rep.last_health = {
            "replica": {"n_slots": 2},
            "kv": {"digest": {"version": 1 if rep.index == 0 else 0}},
        }
    with router._lock:
        rep, how, stale, plan, dec = router._pick_locked(
            None, frozenset(), k[:2]
        )
    assert (rep.index, how, stale, plan) == (0, "cache-aware", False,
                                             None)
    # The decision record carries the audit facts (r15).
    assert dec["hit_depth"] == 2 and len(dec["candidates"]) == 2
    assert dec["holders"] == [{"replica": 0, "tier": "hbm"}]
    assert router.cache_hit_depth_blocks_total == 2
    # Holder past the occupancy watermark (2 inflight / 2 slots = 1.0
    # >= spill_occupancy 1.0): spill to least-loaded + migration plan
    # (score = depth 2 x gap 1.0 = 2.0 >= threshold 1.0).
    router._replicas[0].inflight = 2
    with router._lock:
        rep, how, stale, plan, dec = router._pick_locked(
            None, frozenset(), k[:2]
        )
    assert (rep.index, how) == (1, "spill")
    assert dec["spill_from"] == 0 and dec["handoff_score"] >= 1.0
    assert plan == {"src": 0, "dst": 1, "keys_hex": k[:2], "depth": 2}
    # Cold prompts stay least-loaded.
    with router._lock:
        rep, how, _, plan, _dec = router._pick_locked(
            None, frozenset(), [k[2]]
        )
    assert (rep.index, how, plan) == (1, "least-loaded", None)
    # Scheduler admission: out-of-process replicas cannot handoff.
    router._schedule_handoff(
        {"src": 0, "dst": 1, "keys_hex": k[:2], "depth": 2}, None
    )
    assert router.handoffs_skipped_total == 1
    assert router.handoffs_scheduled_total == 0
    # Unknown policy/roles refusals.
    with pytest.raises(ValueError):
        ReplicaRouter(["127.0.0.1:1"], policy="cache-aware")
    with pytest.raises(ValueError):
        ReplicaRouter(
            ["127.0.0.1:1", "127.0.0.1:2"], policy="cache-aware",
            block_size=16, roles=("prefill", "prefill"),
        )
    with pytest.raises(ValueError):
        ReplicaRouter(
            ["127.0.0.1:1", "127.0.0.1:2"], policy="least-loaded",
            roles=("prefill", "decode"),
        )


def test_stale_digest_detection_counts_and_routes():
    """An index hit whose holder's LIVE digest version moved past the
    synced one is a counted stale route — still routed (locality
    hint), never refused."""
    router = ReplicaRouter(
        ["127.0.0.1:1", "127.0.0.1:2"], policy="cache-aware",
        health_interval_s=0, block_size=16,
    )
    k = ["aa" * 8]
    router.index.replace(
        0, [{"key": k[0], "depth": 1, "tier": "hbm"}], version=1,
    )
    router._replicas[0].last_health = {
        "replica": {"n_slots": 2},
        "kv": {"digest": {"version": 7}},  # moved past synced=1
    }
    with router._lock:
        rep, how, stale, _, _dec = router._pick_locked(
            None, frozenset(), k
        )
    assert (rep.index, how, stale) == (0, "cache-aware", True)
    assert router.cache_stale_routes_total == 1


# ---------------------------------------------------------------------------
# Serving-level handoff hardening: bounds, demote-after-export, unwind
# ---------------------------------------------------------------------------

def test_export_bounds_and_demote_after_export_digest_delta(model):
    """Byte-capped export + demote-after-export: the source's digest
    loses HBM residency for the exported chain (loss_version bumps,
    hbm drops — THE delta that shrinks fleet duplicate bytes) and the
    freed blocks return to the allocator."""
    tok = ByteTokenizer()
    src = _mk_batcher(model, tok)
    _serve_direct(src, tok, [SESSION])
    toks = tok.encode(SESSION, bos=True)
    keys = src._chain_keys(toks, src.block_size)
    assert len(keys) == 2
    # Byte cap truncates block-aligned from the root.
    capped, slabs = src.export_prefix(
        toks, max_bytes=src.block_bytes
    )
    assert len(slabs) == 1 and capped == keys[:1]
    before = src.kv_digest.summary()
    free_before = len(src.free_blocks)
    full_keys, slabs = src.export_prefix(
        keys=keys, demote_after_export=True
    )
    assert len(slabs) == 2 and full_keys == keys
    after = src.kv_digest.summary()
    assert after["hbm_blocks"] == before["hbm_blocks"] - 2
    assert after["loss_version"] > before["loss_version"]
    assert src.kv_export_demoted_blocks_total == 2
    assert len(src.free_blocks) == free_before + 2
    # Nothing resident: a re-export of the same chain is empty.
    assert src.export_prefix(keys=keys) == ([], [])
    # The importing side lands the chain and the next admission is a
    # prefix hit (token identity THROUGH an import is pinned by the
    # disaggregation drill below — one less batcher build here keeps
    # the cell inside the tier-1 budget).
    dst = _mk_batcher(model, tok)
    n = dst.import_prefix(full_keys, slabs)
    assert n == 2
    hits_before = dst.prefix_requests_hit
    got = _serve_direct(dst, tok, [REVISIT], seeds=[5])
    assert len(got[0]) > 0
    assert dst.prefix_requests_hit == hits_before + 1
    assert dst.prefix_hit_tokens_total >= 2 * dst.block_size


def test_import_timeout_unwinds_cleanly(model, monkeypatch):
    """A wedged staged transfer unwinds: blocks freed, nothing
    published, kv_handoff_aborted_total counted — and a later
    unbounded retry of the SAME slabs lands (cancellation-safe)."""
    import jax_llama_tpu.serving as serving_mod

    tok = ByteTokenizer()
    src = _mk_batcher(model, tok)
    _serve_direct(src, tok, [SESSION])
    keys, slabs = src.export_prefix(tok.encode(SESSION, bos=True))
    dst = _mk_batcher(model, tok)
    free_before = len(dst.free_blocks)
    monkeypatch.setattr(
        serving_mod, "restore_ready", lambda staged: False
    )
    with pytest.raises(TimeoutError):
        dst.import_prefix(keys, slabs, timeout_s=0.02)
    assert dst.kv_handoff_aborted_total == 1
    assert len(dst.free_blocks) == free_before
    assert dst.kv_digest.summary()["nodes"] == 0  # no partial publish
    monkeypatch.undo()
    assert dst.import_prefix(keys, slabs, timeout_s=30.0) == len(slabs)
    assert dst.kv_digest.summary()["nodes"] == len(slabs)


# ---------------------------------------------------------------------------
# Routed-fleet acceptance drills
# ---------------------------------------------------------------------------

def _mk_fleet(model, tok, n=2, **router_kw):
    servers = []
    for i in range(n):
        cb = _mk_batcher(model, tok)
        servers.append(
            LLMServer(cb, tokenizer=tok, replica_id=i).start()
        )
    router_kw.setdefault("policy", "cache-aware")
    router_kw.setdefault("health_interval_s", 0)  # manual sync
    router_kw.setdefault("tokenizer", tok)
    router_kw.setdefault("block_size", servers[0].batcher.block_size)
    router = ReplicaRouter(servers, **router_kw).start()
    return router, servers


def test_cache_aware_deep_hit_token_identical_to_oracle(model):
    """ACCEPTANCE PIN: the revisit of a warm session routes to the
    digest-matched replica (not the least-loaded one) and is
    token-identical to the 1-replica oracle — greedy, seeded-sampled,
    and streaming."""
    tok = ByteTokenizer()
    oracle_cb = _mk_batcher(model, tok)
    want_cold = _serve_direct(oracle_cb, tok, [SESSION])
    oracle2 = _mk_batcher(model, tok)
    _serve_direct(oracle2, tok, [SESSION])
    want_greedy = _serve_direct(oracle2, tok, [REVISIT])
    want_seeded = _serve_direct(oracle2, tok, [REVISIT], seeds=[11])

    router, servers = _mk_fleet(model, tok)
    try:
        # Cold session: least-loaded tie-break lands replica 0.
        st, body, hdrs = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want_cold[0]
        warm = int(hdrs["X-Replica-Id"])
        router.check_health_now()  # scrape + index sync
        assert router.index.stats()["nodes"] >= 2
        # A different cold prompt balances onto the OTHER replica...
        st, _, hdrs = _post(
            router.address, {"text": OTHER, "max_new_tokens": 4}
        )
        assert int(hdrs["X-Replica-Id"]) != warm
        # ...but the revisit routes BACK to the warm one by index hit.
        st, body, hdrs = _post(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert st == 200
        assert int(hdrs["X-Replica-Id"]) == warm
        assert body["tokens"] == want_greedy[0]
        st, body, hdrs = _post(
            router.address,
            {"text": REVISIT, "max_new_tokens": 6, "seed": 11},
        )
        assert body["tokens"] == want_seeded[0]
        assert int(hdrs["X-Replica-Id"]) == warm
        toks, hdrs = _stream_tokens(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert toks == want_greedy[0]
        assert int(hdrs["X-Replica-Id"]) == warm
        with router._lock:
            assert router.routed_by_policy["cache-aware"] >= 3
        # The observability surface carries the index + decisions.
        metrics = router.metrics_text()
        assert "llm_router_cache_index_nodes" in metrics
        assert 'policy="cache-aware"' in metrics
        h = router.health()
        assert h["cache_index"]["nodes"] >= 2
        assert h["cache_index"]["syncs_total"] >= 1
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_spill_schedules_handoff_and_chain_migrates(model):
    """The scheduler half: a loaded deepest-prefix holder spills the
    request to least-loaded AND migrates the chain there
    (export -> import through the control paths, demote-after-export
    deduplicating the source).  After migration the next revisit
    routes to the new home, token-identically."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    _serve_direct(oracle, tok, [SESSION])
    want = _serve_direct(oracle, tok, [REVISIT])

    router, servers = _mk_fleet(
        model, tok, handoff_threshold=0.5, handoff_min_depth=1,
    )
    try:
        st, _, hdrs = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        src = int(hdrs["X-Replica-Id"])
        dst = 1 - src
        router.check_health_now()
        src_hbm = servers[src].batcher.kv_digest.summary()["hbm_blocks"]
        assert src_hbm >= 2
        # Pin the holder past the watermark (white-box: the router
        # tracks inflight itself; health said n_slots=2).
        with router._lock:
            router._replicas[src].inflight = 4
        st, body, hdrs = _post(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want[0]
        assert int(hdrs["X-Replica-Id"]) == dst  # spilled
        with router._lock:
            assert router.routed_by_policy["spill"] >= 1
        assert router.wait_handoffs(20.0)
        with router._lock:
            completed = router.handoffs_completed_total
            empty = router.handoffs_empty_total
            scheduled = router.handoffs_scheduled_total
            handoffs = router.kv_handoffs_total
        # Exactly one migration ran: either it landed the slabs
        # (completed) or the spilled request's own cold prefill beat
        # them to the destination (empty — the dedup outcome is the
        # same).  Never aborted, never more than one per chain.
        assert scheduled == 1 and completed + empty == 1
        assert handoffs == completed
        assert router.handoffs_aborted_total == 0
        # The chain MOVED: destination digest holds it HBM-resident,
        # the demoted source lost HBM residency (dedup).
        assert (
            servers[dst].batcher.kv_digest.summary()["hbm_blocks"] >= 2
        )
        assert (
            servers[src].batcher.kv_digest.summary()["hbm_blocks"]
            < src_hbm
        )
        assert servers[src].batcher.kv_export_demoted_blocks_total > 0
        # Un-load the old holder and resync; the revisit routes to the
        # chain's new home, token-identically, as a prefix hit.
        with router._lock:
            router._replicas[src].inflight = 0
        router.check_health_now()
        hits_before = servers[dst].batcher.prefix_requests_hit
        st, body, hdrs = _post(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert body["tokens"] == want[0]
        assert int(hdrs["X-Replica-Id"]) == dst
        assert (
            servers[dst].batcher.prefix_requests_hit == hits_before + 1
        )
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_stale_route_degrades_to_counted_cold_prefill(model):
    """Mid-flight chain loss (loss_version bump after the index
    synced): the route still lands on the old holder, the staleness is
    COUNTED, and the served tokens are identical to the oracle — a
    cold prefill, never wrong tokens."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    _serve_direct(oracle, tok, [SESSION])
    want = _serve_direct(oracle, tok, [REVISIT])

    router, servers = _mk_fleet(model, tok)
    try:
        st, _, hdrs = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        warm = int(hdrs["X-Replica-Id"])
        router.check_health_now()

        # Drop the chain ON the replica (loss_version bumps) without
        # letting the index resync — then refresh only last_health so
        # the router can SEE the version moved.
        def drop_chains(b):
            freed = []
            for blk in list(b._store._by_block.keys()):
                freed.extend(b._store.unpublish(blk))
            b._invalidate_and_free(freed)
            return b.kv_digest.summary()["loss_version"]

        lost = servers[warm].call_on_loop(drop_chains)
        assert lost > 0
        rep = router._replicas[warm]
        ok, payload = router._probe(rep)
        assert ok
        with router._lock:
            rep.last_health = payload
        st, body, hdrs = _post(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want[0]
        assert int(hdrs["X-Replica-Id"]) == warm
        with router._lock:
            assert router.cache_stale_routes_total >= 1
        assert "llm_router_cache_stale_routes_total" in (
            router.metrics_text()
        )
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_mid_handoff_replica_fault_reroutes_losslessly(model):
    """router_replica fault while a handoff is in flight: the request
    re-routes losslessly (pre-byte failure stage) and the tokens stay
    oracle-identical."""
    from jax_llama_tpu.faults import FaultInjector

    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    want = _serve_direct(oracle, tok, [SESSION])

    router, servers = _mk_fleet(
        model, tok,
        fault_injector=FaultInjector("router_replica@1:error"),
        handoff_threshold=0.5,
    )
    try:
        st, body, hdrs = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want[0]
        router.check_health_now()
        # Load the holder and schedule a migration; the SECOND forward
        # (fault index 2) fires mid-handoff and re-routes.
        src = int(hdrs["X-Replica-Id"])
        with router._lock:
            router._replicas[src].inflight = 4
        st, body, _ = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want[0]
        with router._lock:
            assert router.reroutes_total == 1
        assert router.wait_handoffs(20.0)
        router.check_health_now()  # both replicas healthy again
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_prefill_decode_disaggregation_smoke(model):
    """--replica-roles semantics end to end: a cold session prefills
    (and serves) on the prefill replica, its chain streams to the
    decode replica at completion, and the revisit decodes there warm —
    token-identical to the oracle throughout."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    want_cold = _serve_direct(oracle, tok, [SESSION])
    want_rev = _serve_direct(oracle, tok, [REVISIT])

    router, servers = _mk_fleet(
        model, tok, roles=("prefill", "decode"),
    )
    try:
        st, body, hdrs = _post(
            router.address, {"text": SESSION, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want_cold[0]
        assert int(hdrs["X-Replica-Id"]) == 0  # prefill role
        with router._lock:
            assert router.routed_by_policy["prefill-role"] >= 1
        # Completion triggers the prefill -> decode chain stream.
        assert router.wait_handoffs(20.0)
        with router._lock:
            assert router.handoffs_completed_total == 1
        assert (
            servers[1].batcher.kv_digest.summary()["hbm_blocks"] >= 2
        )
        hits_before = servers[1].batcher.prefix_requests_hit
        st, body, hdrs = _post(
            router.address, {"text": REVISIT, "max_new_tokens": 6}
        )
        assert st == 200 and body["tokens"] == want_rev[0]
        assert int(hdrs["X-Replica-Id"]) == 1  # decodes warm
        assert (
            servers[1].batcher.prefix_requests_hit == hits_before + 1
        )
        assert router.health()["roles"] == ["prefill", "decode"]
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_incremental_sync_rides_health_poll(model):
    """The index syncs INCREMENTALLY: after the initial full walk,
    later digest deltas arrive as journal events (resyncs_total stays
    at the initial walks) — and a /debug/kv?since= round-trip through
    the live server carries the events form."""
    tok = ByteTokenizer()
    router, servers = _mk_fleet(model, tok)
    try:
        _post(router.address, {"text": SESSION, "max_new_tokens": 4})
        router.check_health_now()
        st1 = router.index.stats()
        assert st1["nodes"] >= 2
        resyncs_after_first = st1["resyncs_total"]
        _post(router.address, {"text": OTHER, "max_new_tokens": 4})
        router.check_health_now()
        st2 = router.index.stats()
        assert st2["nodes"] > st1["nodes"]
        assert st2["events_applied_total"] >= 1
        assert st2["resyncs_total"] == resyncs_after_first
        # The wire form: since=<current> is an empty event delta.
        ver = servers[0].batcher.kv_digest.summary()["version"]
        with urllib.request.urlopen(
            servers[0].address + f"/debug/kv?since={ver}", timeout=30
        ) as r:
            doc = json.loads(r.read())
        assert doc["events"] == [] and doc["version"] == ver
        # since far past the version (stale consumer of a rebuilt
        # digest) falls back to the resync walk.
        with urllib.request.urlopen(
            servers[0].address + f"/debug/kv?since={ver + 9999}",
            timeout=30,
        ) as r:
            doc = json.loads(r.read())
        assert doc.get("resync") is True and "nodes" in doc
    finally:
        router.stop()
        for s in servers:
            s.stop()
