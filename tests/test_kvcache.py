"""KV-capacity subsystem (kvcache.py): radix prefix index + host-DRAM
block tier (make kvcache; tier-1-safe, CPU).

The invariants pinned here:
  * radix-hit admissions are TOKEN- AND LOGPROB-IDENTICAL to cold
    prefill across {greedy, seeded-sampled} x {hit depth 0 / partial /
    full} x {fp32, int8-KV} x {fused, classic admission} — a hit (at
    any depth, through either scheduler) changes what is computed,
    never what is emitted.  int8 oracles are CHUNK-MATCHED (chunk
    boundaries decide where prompt KV quantizes — the PR-5 rule);
  * the radix tree shares divergent chains' common prefix by
    construction and never mints duplicate nodes;
  * eviction under allocation pressure only ever takes refcount-0
    blocks — live (refcounted) shared blocks survive;
  * demote -> restore through the host tier is BIT-EXACT at the pool
    level (including int8 scales and the draft-pool twin) and
    token-identical at the serving level;
  * a swap-in in flight never stalls decode: every mid-swap chunk
    dispatch keeps emitting at an un-collapsed K, and the restored
    admission pays <= 1 state upload (the fused-admission budget) —
    the ``make perf-smoke`` contract.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.kvcache import (
    RadixPrefixStore,
    adopt_into_pool,
    fetch_slab,
    make_prefix_store,
    stage_restore,
)
from jax_llama_tpu.serving import ContinuousBatcher, init_pool

pytestmark = pytest.mark.kvcache

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=256, dtype="float32", param_dtype="float32",
)
BS = 16  # block size used throughout


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


# ---------------------------------------------------------------------------
# Radix store mechanics (no model)
# ---------------------------------------------------------------------------

def _fake_chain(n):
    return [bytes([i]) * 8 for i in range(n)]


def test_chain_extension_after_partial_hit_stays_matchable(model):
    """REGRESSION (found by the r13 chain digest): a session that HIT
    a cached prefix and extended it used to publish only its suffix —
    the radix publish walk starts at the root, so the extension nodes
    mis-rooted under mid-chain keys: unreachable for matching (a
    revisit hit only the old depth) and depth-wrong in /debug/kv.
    Both admission paths must publish the FULL chain so extensions
    parent correctly and a revisit matches end-to-end."""
    params, config = model
    base = list(np.random.RandomState(0).randint(1, 128, 40))
    ext = base[:32] + list(np.random.RandomState(1).randint(1, 128, 40))
    for kw in (
        {},                                      # classic admission
        {"prefill_budget": 32, "decode_chunk": 4},  # fused lane
    ):
        # Geometry matches the module's parity matrix so the jit
        # cache is shared — this test adds no compiles of its own.
        cb = ContinuousBatcher(
            params, config, n_slots=2, max_len=256, block_size=BS, **kw
        )
        cb.submit(base, max_new_tokens=4)
        cb.run_to_completion()
        cb.submit(ext, max_new_tokens=4)  # partial hit + extension
        cb.run_to_completion()
        keys = cb._chain_keys(ext, BS)
        assert len(keys) == 4
        # The whole extended chain is matchable...
        assert len(cb._match_prefix(keys).blocks) == 4, kw
        # ...and the digest sees one chain of depths 1..4, not two
        # root-parented stumps.
        depths = sorted(
            n["depth"] for n in cb.kv_debug_json()["nodes"]
        )
        assert depths == [1, 2, 3, 4], kw


def test_radix_publish_match_and_dedup():
    store = RadixPrefixStore()
    keys = _fake_chain(3)
    store.publish(keys, [10, 11, 12])
    m = store.match(keys)
    assert m.blocks == [10, 11, 12] and not m.restore
    assert store.match(keys[:2]).blocks == [10, 11]
    assert store.match([b"zz" * 4] + keys).blocks == []
    # Divergent chain sharing the first two nodes: one new node only.
    keys2 = keys[:2] + [b"\xff" * 8]
    store.publish(keys2, [10, 11, 13])
    assert store.nodes_total() == 4
    # Duplicate publication keeps the existing blocks; the fresh copies
    # stay unkeyed.
    store.publish(keys, [20, 21, 22])
    assert store.match(keys).blocks == [10, 11, 12]
    assert not store.is_keyed(20)


def test_radix_eviction_is_leaves_first():
    """Dropping (no tier) must never strand a resident suffix: an idle
    interior node with resident children is skipped in favor of a
    leaf, whatever the LRU order says."""
    store = RadixPrefixStore()
    keys = _fake_chain(3)
    store.publish(keys, [10, 11, 12])
    # Retain PARENT-first (the adversarial order; the batcher's
    # _free_slot hands chains in order and the store reverses).
    store.retain([10, 11, 12])
    got = []
    while store.evictable():
        blk, extra = store.pop_evictable(None)
        got.append(blk)
        assert not extra  # leaves-first never strands anything
    assert got == [12, 11, 10]  # back-to-front despite LRU front = 10


def test_radix_unpublish_drops_subtree():
    """The non-finite guard's contract: unpublishing a suspect block
    removes its whole subtree (deeper chain blocks are only reachable
    through it), returning stranded idle blocks for freeing."""
    store = RadixPrefixStore()
    keys = _fake_chain(3)
    store.publish(keys, [10, 11, 12])
    store.retain([12])  # leaf idle; 10/11 still "live" (no refs here)
    freed = store.unpublish(11)
    assert freed == [12]  # the stranded idle leaf
    assert store.nodes_total() == 1  # only the root child survives
    assert store.match(keys).blocks == [10]


def test_host_tier_demote_keeps_node_matchable():
    store = make_prefix_store("radix", host_blocks=4)
    keys = _fake_chain(2)
    store.publish(keys, [10, 11])
    store.retain([10, 11])
    blk, extra = store.pop_evictable(lambda b: {"fake": np.zeros(2)})
    assert blk == 11 and not extra
    assert store.host_blocks() == 1
    m = store.match(keys)
    assert m.blocks == [10]           # resident prefix
    assert len(m.restore) == 1        # demoted node still on the path
    assert m.restore[0].host is not None
    # Completing a restore re-anchors the node on its fresh block.
    store.pin_restoring(m.restore)
    assert store.match(keys).blocks == [10]  # restoring = unreachable
    store.complete_restore(m.restore, [42])
    assert store.match(keys).blocks == [10, 42]
    assert store.host_blocks() == 0


def test_host_tier_lru_capacity():
    """The tier holds at most ``host_blocks`` slabs; overflow evicts the
    oldest unpinned slab and its node (plus any now-unreachable
    subtree) drops."""
    store = make_prefix_store("radix", host_blocks=2)
    keys = _fake_chain(3)
    store.publish(keys, [10, 11, 12])
    store.retain([10, 11, 12])
    extras = []
    for _ in range(3):
        _, extra = store.pop_evictable(lambda b: {"fake": np.zeros(2)})
        extras.extend(extra)
    assert store.host_blocks() == 2
    assert not extras  # demotions themselves strand nothing


# ---------------------------------------------------------------------------
# Demote -> restore round trip (pool-level bit-exactness)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("int8", [False, True])
def test_demote_restore_round_trip_bit_exact(model, int8):
    _, config = model
    if int8:
        config = dataclasses.replace(config, kv_cache_dtype="int8")
    pool = init_pool(config, n_blocks=4, block_size=8)
    rng = np.random.RandomState(0)

    def fill(pool):
        reps = {}
        for name in ("k", "v", "pos", "k_scale", "v_scale"):
            a = getattr(pool, name)
            if a is None:
                continue
            if a.dtype == jnp.int8:
                v = rng.randint(-127, 127, size=a.shape).astype(np.int8)
            elif a.dtype == jnp.int32:
                v = rng.randint(0, 50, size=a.shape).astype(np.int32)
            else:
                v = rng.randn(*a.shape).astype(np.asarray(a).dtype)
            reps[name] = jnp.asarray(v)
        return dataclasses.replace(pool, **reps)

    pool = fill(pool)
    want = {n: np.asarray(getattr(pool, n)[:, :, 2])
            for n in ("k", "v", "k_scale", "v_scale")
            if getattr(pool, n) is not None}
    want["pos"] = np.asarray(pool.pos[2])

    slab = fetch_slab(pool, 2)
    # Clobber the block (what reallocation does), then restore it into
    # a DIFFERENT physical block — content must round-trip bit-exact.
    staged = stage_restore([slab], [1], sentinel=4)
    jax.block_until_ready(list(staged.values()))
    pool = adopt_into_pool(pool, staged)
    for name, w in want.items():
        arr = getattr(pool, name)
        got = np.asarray(arr[1] if name == "pos" else arr[:, :, 1])
        np.testing.assert_array_equal(got, w, err_msg=name)


# ---------------------------------------------------------------------------
# Serving-level parity matrix
# ---------------------------------------------------------------------------

def _drain(cb, rid):
    """Step until ``rid`` finishes (other rows may stay live — a
    resident decode row must survive, or a later probe would land on a
    cold pool and admit classically); returns (tokens, logprobs) for
    ``rid`` (logprobs empty without logprobs mode)."""
    toks, lps = [], []
    guard = 0
    done = False
    while not done:
        guard += 1
        assert guard < 400
        if not cb.pending():
            break
        for tup in cb.step():
            if tup[0] == rid:
                toks.append(tup[1])
                if len(tup) > 3:
                    lps.append(float(tup[3]))
                done = done or bool(tup[2])
    return toks, lps


def _assert_parity(got, want, ctx):
    """Tokens exact; logprobs to fp32-noise tolerance (the oracle runs
    a differently-SHAPED dispatch — XLA may fuse differently, the
    PR-5 comparison discipline)."""
    assert got[0] == want[0], ctx
    np.testing.assert_allclose(
        got[1], want[1], rtol=1e-5, atol=1e-6, err_msg=str(ctx)
    )


def _submit(cb, tokens, sampling):
    kw = dict(max_new_tokens=6)
    if sampling == "sampled":
        kw.update(temperature=0.8, seed=7)
    return cb.submit(list(tokens), **kw)


# The full matrix rides the slow tier (make kvcache / pytest -m
# kvcache runs it all; tier-1 keeps the smoke slice below) — the PR-2
# slow-marker rebalance discipline that keeps tier-1 inside its 870 s
# budget.
@pytest.mark.slow
@pytest.mark.parametrize("int8", [
    pytest.param(False, id="fp32"),
    pytest.param(True, id="int8"),
])
@pytest.mark.parametrize("sampling", ["greedy", "sampled"])
def test_radix_hit_parity_matrix(model, sampling, int8):
    """radix-hit ≡ cold-prefill, tokens AND logprobs, across hit depth
    {0, partial, full} x {fused, classic} admission.  The seed request
    establishes a chain whose first 2 blocks (32 tokens) the partial
    probe shares and the full probe matches entirely; the cold oracle
    runs prefix_cache=False at MATCHED prefill chunking (int8-KV
    quantizes prompt KV at chunk boundaries, so the oracle must cut
    the prompt where the warm path does — depth-0 classic admission is
    the one case whose warm dispatch is itself a single-shot insert)."""
    params, config = model
    if int8:
        config = dataclasses.replace(config, kv_cache_dtype="int8")
    rng = np.random.RandomState(21)
    prefix = rng.randint(1, 128, size=32).tolist()     # 2 full blocks
    seed_prompt = prefix + rng.randint(1, 128, size=8).tolist()
    probes = {
        "zero": rng.randint(1, 128, size=64).tolist(),  # shares nothing
        "partial": prefix + rng.randint(1, 128, size=32).tolist(),
        "full": list(seed_prompt),                      # all keyed blocks
    }
    expected_hit_blocks = {"zero": 0, "partial": 2, "full": 2}

    for admission in ("classic", "fused"):
        for depth, probe in probes.items():
            oracle_chunk = (
                None if (admission, depth) == ("classic", "zero") else 32
            )
            cold = ContinuousBatcher(
                params, config, n_slots=2, max_len=256, block_size=BS,
                prefix_cache=False, logprobs=True,
                prefill_chunk=oracle_chunk,
            )
            want = _drain(cold, _submit(cold, probe, sampling))

            warm = ContinuousBatcher(
                params, config, n_slots=2, max_len=256, block_size=BS,
                prefix_cache=True, logprobs=True,
                decode_chunk=4 if admission == "fused" else 1,
                prefill_budget=32 if admission == "fused" else 0,
            )
            if admission == "fused":
                # A resident decoding row (long-lived: it must still be
                # decoding when the PROBE admits, or the fused lane
                # never engages) makes the probe ride the fused
                # prefill lane — cold pools admit classically.
                warm.submit([3, 5, 9], max_new_tokens=120)
                warm.step()
                warm.step()
            r0 = _submit(warm, seed_prompt, sampling)
            _drain(warm, r0)  # publish the chain
            h0 = warm.stats()["prefix_blocks_reused_total"]
            f0 = warm.fused_admissions_total
            got = _drain(warm, _submit(warm, probe, sampling))
            reused = warm.stats()["prefix_blocks_reused_total"] - h0
            if admission == "fused":
                # The probe really rode the fused prefill lane.
                assert warm.fused_admissions_total > f0, (depth, int8)
            _assert_parity(got, want, (admission, depth, sampling, int8))
            # Partial-prefix admission reuses >= the matched blocks.
            assert reused >= expected_hit_blocks[depth], (
                admission, depth
            )


def test_radix_hit_parity_smoke(model):
    """Tier-1 slice of the matrix above: the strictest cheap cell —
    seeded-sampled fp32, PARTIAL hit depth, classic admission
    (seeded-sampled consumes the key chains greedy never touches;
    partial depth exercises the mid-chain radix walk; the fused ×
    restored lane runs in tier-1 via
    test_swap_in_flight_never_stalls_decode)."""
    params, config = model
    rng = np.random.RandomState(21)
    prefix = rng.randint(1, 128, size=32).tolist()
    seed_prompt = prefix + rng.randint(1, 128, size=8).tolist()
    probe = prefix + rng.randint(1, 128, size=32).tolist()

    cold = ContinuousBatcher(params, config, n_slots=2, max_len=256,
                             block_size=BS, prefix_cache=False,
                             logprobs=True, prefill_chunk=32)
    want = _drain(cold, _submit(cold, probe, "sampled"))
    warm = ContinuousBatcher(params, config, n_slots=2, max_len=256,
                             block_size=BS, prefix_cache=True,
                             logprobs=True)
    _drain(warm, _submit(warm, seed_prompt, "sampled"))
    got = _drain(warm, _submit(warm, probe, "sampled"))
    assert warm.stats()["prefix_blocks_reused_total"] >= 2
    _assert_parity(got, want, "classic")


# slow (r17 budget rebalance, ~11 s): refcount-guarded eviction with
# live sharers stays tier-1-pinned at the prefix-cache layer
# (test_prefix_cache.py::test_eviction_under_pressure_stays_correct and
# test_cancel_sharer_keeps_other_alive); this radix-layer re-proof rides
# slow (`make kvcache` selects by marker, so it still runs there).
@pytest.mark.slow
def test_eviction_under_pressure_keeps_live_refcounted_blocks(model):
    """Allocation pressure while SHARERS are live: only refcount-0
    (idle) blocks may be evicted — the live shared prefix survives and
    both sharers finish token-identically to a cold run."""
    params, config = model
    rng = np.random.RandomState(31)
    # Pool of 16 blocks, max_len 128 (8 blocks/slot).
    idle_chain = rng.randint(1, 128, size=40).tolist()  # keys 2 blocks
    shared = rng.randint(1, 128, size=40).tolist()
    a, b = shared + [3], shared + [9, 4]

    cb = ContinuousBatcher(params, config, n_slots=3, max_len=128,
                           block_size=BS, n_blocks=12, prefix_cache=True)
    cb.submit(list(idle_chain), max_new_tokens=4)
    cb.run_to_completion()           # 2 idle keyed blocks
    cb.submit(list(shared) + [7], max_new_tokens=4)
    cb.run_to_completion()           # 2 more idle keyed blocks
    ra = cb.submit(list(a), max_new_tokens=8)
    rb = cb.submit(list(b), max_new_tokens=8)
    got = {ra: [], rb: []}
    for tup in cb.step():            # both sharers admitted, live
        got[tup[0]].append(tup[1])
    live_blocks = set()
    for s in cb.slots.values():
        if s is not None:
            live_blocks.update(s.blocks)
    # The filler's 6-block reservation exceeds the 4 free blocks while
    # the sharers hold theirs, so eviction must reclaim idle blocks —
    # and the only refcount-0 candidates are the IDLE chain's; the
    # sharers' live (claimed) shared blocks are untouchable.
    idle_keys = cb._chain_keys(idle_chain, BS)
    assert len(cb._store.match(idle_keys).blocks) == 2  # resident now
    assert len(cb.free_blocks) == 4
    filler = rng.randint(1, 128, size=80).tolist()
    cb.submit(filler, max_new_tokens=8)
    while cb.pending():
        for tup in cb.step():
            if tup[0] in got:
                got[tup[0]].append(tup[1])
    # Eviction took the idle chain (no tier: dropped), not the live one.
    assert len(cb._store.match(idle_keys).blocks) < 2
    assert live_blocks  # the sharers really held blocks mid-pressure
    cold = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                             block_size=BS, prefix_cache=False)
    ca = cold.submit(list(a), max_new_tokens=8)
    cbq = cold.submit(list(b), max_new_tokens=8)
    cres = cold.run_to_completion()
    assert got[ra] == cres[ca]
    assert got[rb] == cres[cbq]


# ---------------------------------------------------------------------------
# Host tier at the serving level
# ---------------------------------------------------------------------------

def _tier_batcher(params, config, **kw):
    """Small pool + host tier: geometry chosen so one big filler
    reservation forces the idle session chain to demote."""
    kwargs = dict(
        n_slots=2, max_len=128, block_size=BS, n_blocks=8,
        prefix_cache=True, host_kv_blocks=4,
    )
    kwargs.update(kw)
    return ContinuousBatcher(params, config, **kwargs)


def _seed_and_demote(cb, session, rng):
    """Complete ``session`` (2 keyed blocks retained), then run a
    filler whose reservation needs every free block PLUS the idle
    chain — the chain demotes into the host tier."""
    rid = cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    filler = rng.randint(1, 128, size=112).tolist()  # 7 blocks + 1
    cb.submit(filler, max_new_tokens=8)
    cb.run_to_completion()
    assert cb.stats()["host_tier_blocks"] >= 1
    return rid


@pytest.mark.parametrize("sampling", [
    pytest.param("greedy", marks=pytest.mark.slow),
    "sampled",
])
def test_demote_restore_token_identical(model, sampling):
    """A session whose cached prefix was demoted to the host tier
    admits through the ``restoring`` state (async swap-in + adoption)
    and emits exactly the cold batcher's tokens and logprobs."""
    params, config = model
    rng = np.random.RandomState(41)
    session = rng.randint(1, 128, size=40).tolist()

    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=BS, prefix_cache=False,
                             logprobs=True)
    want = _drain(cold, _submit(cold, session, sampling))

    cb = _tier_batcher(params, config, logprobs=True)
    _seed_and_demote(cb, session, rng)
    # The filler evicted the session chain into the tier; now the
    # session comes back — its admission must swap the blocks in.
    got = _drain(cb, _submit(cb, session, sampling))
    st = cb.stats()
    _assert_parity(got, want, sampling)
    assert st["swap_ins_total"] == 1
    assert st["swap_in_blocks_total"] == 2
    assert st["swap_out_blocks_total"] >= 2
    assert st["swap_in_ms_total"] > 0
    assert st["prefix_requests_hit_total"] == 1
    assert st["prefix_blocks_reused_total"] == 2  # the restored depth


@pytest.mark.slow
def test_more_live_sessions_than_hbm_pool_completes_via_tier(model):
    """The capacity headline: a workload of sessions whose combined KV
    exceeds the HBM pool completes with every revisit hitting the
    cache (restored from the tier), no live block ever evicted, and
    cold re-prefills only on the first visit."""
    params, config = model
    rng = np.random.RandomState(43)
    sessions = [rng.randint(1, 128, size=40).tolist() for _ in range(3)]
    # Pool: 6 blocks = 1.5 sessions' reservations (each needs 4);
    # tier: 8 more — the three sessions' retained chains cannot all be
    # HBM-resident, so revisits must come back through the tier.
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           block_size=BS, n_blocks=6, prefix_cache=True,
                           host_kv_blocks=8)
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                             block_size=BS, prefix_cache=False)
    # Visit each session twice, round-robin: second visits must hit
    # (HBM or tier) and match cold outputs.
    for round_i in range(2):
        for s in sessions:
            rid = cb.submit(list(s), max_new_tokens=8)
            got = cb.run_to_completion()[rid]
            crid = cold.submit(list(s), max_new_tokens=8)
            assert got == cold.run_to_completion()[crid]
    st = cb.stats()
    assert st["prefix_requests_hit_total"] == 3   # every revisit hit
    assert st["swap_ins_total"] >= 1              # at least one from tier
    assert st["swap_failures_total"] == 0


def test_swap_in_flight_never_stalls_decode(model):
    """The perf-smoke contract: while a swap-in is in flight
    (``swap_poll_min`` holds the restoring window open), every chunk
    dispatch keeps emitting from the resident decode row at an
    UN-COLLAPSED K, and the restored admission pays <= 1 state upload
    — the same budget as a fused admission."""
    params, config = model
    rng = np.random.RandomState(47)
    session = rng.randint(1, 128, size=40).tolist()
    cb = _tier_batcher(
        params, config, n_slots=2, n_blocks=12,
        decode_chunk=4, prefill_budget=16,
    )
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    # Deterministic demotion (the operational lever; the pressure path
    # is covered by test_demote_restore_token_identical).
    assert cb.demote_idle(2) == 2
    assert cb.stats()["host_tier_blocks"] == 2
    # Resident decoding row, chunk size ramped to 4.
    r0 = cb.submit([3, 5, 9], max_new_tokens=60)
    cb.step()
    cb.step()
    cb.step()
    assert cb.decode_chunk_last == 4
    # Hold the swap-in open for 3 polls so the overlap is observable.
    cb.swap_poll_min = 3
    u0 = cb.state_uploads_total
    rid = cb.submit(list(session), max_new_tokens=4)
    saw_restoring = 0
    first = {rid: None}
    guard = 0
    while first[rid] is None:
        guard += 1
        assert guard < 30
        evs = cb.step()
        if cb._restoring:
            saw_restoring += 1
            # Mid-swap: the resident row kept emitting a full chunk —
            # zero stall dispatches, K un-collapsed.
            assert cb.decode_chunk_last == 4
            assert any(ev[0] == r0 for ev in evs)
        for ev in evs:
            if ev[0] == rid and first[rid] is None:
                first[rid] = ev[1]
    assert saw_restoring >= 2          # the window really was open
    assert cb.stats()["swap_queue_depth"] == 0
    # The whole restored admission cost <= 1 dirty-row state upload.
    assert cb.state_uploads_total - u0 <= 1
    while cb.pending():
        cb.step()
    assert cb.stats()["decode_stall_ms_total"] == 0.0


def test_cancel_mid_restore_unpins_everything(model):
    """Cancelling a restoring request releases its claims: the nodes
    fall back to host residency, the fresh blocks return to the free
    list, and a later resubmit restores cleanly."""
    params, config = model
    rng = np.random.RandomState(53)
    session = rng.randint(1, 128, size=40).tolist()
    cb = _tier_batcher(params, config, n_slots=2, n_blocks=12,
                       decode_chunk=4, prefill_budget=16)
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    assert cb.demote_idle(2) == 2
    cb.submit([3, 5, 9], max_new_tokens=40)
    cb.step()
    cb.step()
    cb.swap_poll_min = 100  # keep the restore in flight
    cap0 = cb._capacity()
    refs0 = dict(cb._block_refs)
    rid = cb.submit(list(session), max_new_tokens=4)
    cb.step()
    assert cb.stats()["swap_queue_depth"] == 1
    assert cb.cancel(rid)
    assert cb.stats()["swap_queue_depth"] == 0
    assert cb.stats()["host_tier_blocks"] >= 2  # slabs intact
    # Leak regression: the restore CLAIMED both its resident hits and
    # its fresh blocks — cancel must unclaim (not just free) them, or
    # pool capacity and the refcount table drift permanently.
    assert cb._capacity() == cap0
    assert cb._block_refs == refs0
    cb.swap_poll_min = 0
    # Resubmit: restores and completes fine.
    rid2 = cb.submit(list(session), max_new_tokens=4)
    got = []
    while cb.pending():
        for tup in cb.step():
            if tup[0] == rid2:
                got.append(tup[1])
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=BS, prefix_cache=False)
    cr = cold.submit(list(session), max_new_tokens=4)
    assert got == cold.run_to_completion()[cr]


def test_broken_restore_path_requeues_cold(model):
    """A non-finite unpublish that severs a restore's matched path
    mid-swap (another request on the shared chain poisons) must not
    crash admission with nulled node.block entries: the poll detects
    the broken path, unwinds the claims, and requeues the request at
    the head for a clean cold prefill — token-identical."""
    params, config = model
    rng = np.random.RandomState(61)
    session = rng.randint(1, 128, size=40).tolist()
    cb = _tier_batcher(params, config, n_slots=2, n_blocks=12,
                       decode_chunk=4, prefill_budget=16)
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    # Demote only the LEAF: the restore's path mixes one resident hit
    # (the parent) with one host-tier node — the mixed shape finding 2
    # needs.
    assert cb.demote_idle(1) == 1
    cb.submit([3, 5, 9], max_new_tokens=40)
    cb.step()
    cb.swap_poll_min = 100  # hold the swap-in open
    rid = cb.submit(list(session), max_new_tokens=4)
    cb.step()
    assert cb.stats()["swap_queue_depth"] == 1
    r = cb._restoring[0]
    assert r.resident and r.restore
    # Sever the path the way _fail_slot's guard does: drop the
    # resident parent's subtree (takes the restoring leaf with it).
    cb._invalidate_and_free(cb._store.unpublish(r.resident[0]))
    cb.swap_poll_min = 0
    cb.step()
    assert cb.stats()["swap_queue_depth"] == 0  # aborted, requeued
    got = []
    while cb.pending():
        for tup in cb.step():
            if tup[0] == rid:
                got.append(tup[1])
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                             block_size=BS, prefix_cache=False)
    cr = cold.submit(list(session), max_new_tokens=4)
    assert got == cold.run_to_completion()[cr]


@pytest.mark.slow
def test_spec_batcher_tier_round_trip(model):
    """The draft pool's KV demotes and restores alongside the target's
    (``d_``-prefixed slab twins): a speculative batcher with the tier
    emits identically to a cold speculative batcher after a
    demote -> restore cycle."""
    params, config = model
    draft_config = get_config(
        "tiny", **{**CFG, "dim": 32, "n_layers": 1, "n_heads": 2,
                   "n_kv_heads": 1}
    )
    draft_params = init_params(jax.random.PRNGKey(1), draft_config)
    rng = np.random.RandomState(59)
    session = rng.randint(1, 128, size=40).tolist()

    def build(**kw):
        return ContinuousBatcher(
            params, config, n_slots=1, max_len=128, block_size=BS,
            draft_params=draft_params, draft_config=draft_config,
            n_draft=2, **kw,
        )

    cold = build(prefix_cache=False)
    cr = cold.submit(list(session), max_new_tokens=8)
    want = cold.run_to_completion()[cr]

    cb = build(n_blocks=8, prefix_cache=True, host_kv_blocks=4)
    _seed_and_demote(cb, session, rng)
    rid = cb.submit(list(session), max_new_tokens=8)
    got = cb.run_to_completion()[rid]
    assert got == want
    assert cb.stats()["swap_ins_total"] == 1


def test_metrics_surface(model):
    """The KV-capacity gauges are in stats() (and therefore in the
    HTTP /metrics exposition), with prefix_cached_blocks preserved as
    the pre-radix alias."""
    params, config = model
    cb = _tier_batcher(params, config)
    rng = np.random.RandomState(61)
    session = rng.randint(1, 128, size=40).tolist()
    _seed_and_demote(cb, session, rng)
    cb.submit(list(session), max_new_tokens=4)
    cb.run_to_completion()
    stats = cb.stats()
    for key in (
        "radix_nodes_total", "prefix_hit_tokens_ratio",
        "host_kv_blocks", "host_tier_blocks", "swap_queue_depth",
        "swap_ins_total", "swap_in_blocks_total",
        "swap_out_blocks_total", "swap_in_ms_total",
        "swap_failures_total", "prefix_cached_blocks",
    ):
        assert key in stats, key
    assert stats["radix_nodes_total"] > 0
    assert 0 < stats["prefix_hit_tokens_ratio"] < 1
    assert stats["host_kv_blocks"] == 4
    assert stats["swap_queue_depth"] == 0
