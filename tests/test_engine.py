"""Decode-engine tests: greedy decode must equal repeated full-recompute
argmax (the tier-3 analogue of the reference's exact-string greedy parity,
jax_test.py:492-522 — here the oracle is the framework's own no-cache
forward, which is itself parity-tested against torch in test_model.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.engine import GenerationConfig, generate, prompt_positions
from jax_llama_tpu.generation import LLaMA
from jax_llama_tpu.models import forward, init_params
from jax_llama_tpu.tokenizers import ByteTokenizer

CFG = cfg_lib.tiny(max_seq_len=128)
PARAMS = init_params(jax.random.PRNGKey(0), CFG)


def _greedy_reference(params, prompt, max_new):
    """Slow oracle: re-run the full no-cache forward for every token."""
    toks = list(prompt)
    for _ in range(max_new):
        positions = np.arange(len(toks))[None, :]
        logits, _ = forward(
            params, jnp.asarray([toks]), jnp.asarray(positions), CFG
        )
        toks.append(int(np.asarray(logits)[0, -1].argmax()))
    return toks[len(prompt):]


def test_greedy_decode_matches_full_recompute():
    prompt = [5, 17, 200, 3, 42]
    gc = GenerationConfig(max_new_tokens=12, temperature=0.0)
    out = generate(
        PARAMS,
        jnp.asarray([prompt], dtype=jnp.int32),
        jnp.ones((1, len(prompt)), dtype=bool),
        jax.random.PRNGKey(0),
        config=CFG,
        gen_config=gc,
    )
    got = np.asarray(out)[0, len(prompt):].tolist()
    want = _greedy_reference(PARAMS, prompt, 12)
    assert got == want


def test_left_padded_batch_matches_individual_greedy():
    prompts = [[5, 17, 200], [9, 1, 2, 3, 4, 250]]
    P = max(len(p) for p in prompts)
    pad = 0
    tokens = np.full((2, P), pad, np.int32)
    mask = np.zeros((2, P), bool)
    for i, p in enumerate(prompts):
        tokens[i, P - len(p):] = p
        mask[i, P - len(p):] = True
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0)
    out = np.asarray(generate(
        PARAMS, jnp.asarray(tokens), jnp.asarray(mask),
        jax.random.PRNGKey(0), config=CFG, gen_config=gc,
    ))
    for i, p in enumerate(prompts):
        want = _greedy_reference(PARAMS, p, 8)
        assert out[i, P:].tolist() == want, f"row {i}"


def test_stop_token_halts_row_and_pads_rest():
    # Find what greedy emits, then declare a stop at the first emission
    # whose value does not also occur earlier (so halting at the first
    # occurrence is unambiguous).
    prompt = [5, 17, 200, 3, 42]
    emitted = _greedy_reference(PARAMS, prompt, 6)
    # (max, not first: keeps some decode before the stop; i=0 always
    # qualifies, so this never fails even on degenerate repeat loops)
    j = max(
        i for i in range(len(emitted)) if emitted[i] not in emitted[:i]
    )
    stop = emitted[j]
    gc = GenerationConfig(
        max_new_tokens=6, temperature=0.0, stop_tokens=(stop,), pad_id=255
    )
    out = np.asarray(generate(
        PARAMS, jnp.asarray([prompt], dtype=jnp.int32),
        jnp.ones((1, len(prompt)), bool),
        jax.random.PRNGKey(0), config=CFG, gen_config=gc,
    ))[0, len(prompt):]
    assert out[j] == stop              # the stop token itself is kept
    assert (out[j + 1:] == 255).all()  # then pad forever


def test_sampled_decode_is_reproducible_and_varies_with_seed():
    prompt = jnp.asarray([[5, 17, 200]], dtype=jnp.int32)
    mask = jnp.ones((1, 3), bool)
    gc = GenerationConfig(max_new_tokens=10, temperature=1.0, top_p=0.9)
    a = np.asarray(generate(PARAMS, prompt, mask, jax.random.PRNGKey(1),
                            config=CFG, gen_config=gc))
    b = np.asarray(generate(PARAMS, prompt, mask, jax.random.PRNGKey(1),
                            config=CFG, gen_config=gc))
    c = np.asarray(generate(PARAMS, prompt, mask, jax.random.PRNGKey(2),
                            config=CFG, gen_config=gc))
    assert (a == b).all()
    assert (a != c).any()


def test_prompt_positions():
    mask = jnp.asarray([[False, False, True, True], [True, True, True, True]])
    got = np.asarray(prompt_positions(mask))
    np.testing.assert_array_equal(got, [[-1, -1, 0, 1], [0, 1, 2, 3]])


def test_generate_from_str_roundtrip():
    tok = ByteTokenizer()
    cfg = cfg_lib.tiny(vocab_size=len(tok), max_seq_len=128)
    params = init_params(jax.random.PRNGKey(1), cfg)
    model = LLaMA(params=params, config=cfg, tokenizer=tok)
    outs = model.generate_from_str(
        ["hello", "a longer prompt here"], max_gen_len=8, temperature=0.0
    )
    assert len(outs) == 2
    assert all(isinstance(o, str) for o in outs)
    # Greedy must be deterministic across calls.
    outs2 = model.generate_from_str(
        ["hello", "a longer prompt here"], max_gen_len=8, temperature=0.0
    )
    assert outs == outs2


@pytest.mark.slow
def test_auto_impl_decode_matches_full_forward():
    """attn_impl='auto' mixes flash prefill (T>8) with the append-free xla
    decode path (T==1); chunked decode must still match the full forward.

    Slow tier (PR-10 budget rebalance: tier-1 measured at its 870 s
    ceiling): the auto-impl composition stays pinned tier-1 by
    test_flash_attention.py (flash ≡ xla numerics), the chunked-prefill
    identity below, and the serving fused suite (flash prefill chunks
    under attn auto vs the classic path); this full-forward cross-check
    runs in the unfiltered suite and `make chaos`-class targets."""
    import numpy as np
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.models import forward
    from jax_llama_tpu.models.llama import init_cache

    config = get_config(
        "tiny", vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64, attn_impl="auto",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 32
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (B, T)), jnp.int32
    )
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    full, _ = forward(params, tokens, pos, config)
    want = np.asarray(full)

    # prefill 16 (flash), then 16 single-token xla decode steps
    cache = init_cache(config, B, max_len=T)
    lg, cache = forward(
        params, tokens[:, :16], pos[:, :16], config, cache=cache
    )
    outs = [np.asarray(lg)]
    for i in range(16, T):
        lg, cache = forward(
            params, tokens[:, i:i + 1], pos[:, i:i + 1], config, cache=cache
        )
        outs.append(np.asarray(lg))
    got = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(got, want, atol=3e-4, rtol=3e-4)


# slow (r17 budget rebalance, ~11 s): the engine loop stays tier-1-pinned
# by test_greedy_decode_matches_full_recompute and chunked-prefill token
# identity stays tier-1-pinned at the serving layer
# (test_serving.py::test_chunked_admission_matches_single_shot plus
# test_serving_chunked.py's matrix); the engine-layer chunking drill
# rides slow (unfiltered suite runs it).
@pytest.mark.slow
def test_chunked_prefill_matches_single_shot():
    """Chunked prefill (incl. a ragged final chunk) must generate exactly
    the same tokens as single-shot prefill."""
    import numpy as np
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config(
        "tiny", vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, P = 2, 20  # 20 % 8 != 0 -> ragged last chunk
    rng = np.random.RandomState(0)
    tokens = np.full((B, P), 0, np.int32)
    mask = np.zeros((B, P), bool)
    for b in range(B):
        n = rng.randint(5, P + 1)
        tokens[b, P - n:] = rng.randint(1, 128, n)
        mask[b, P - n:] = True
    tokens, mask = jnp.asarray(tokens), jnp.asarray(mask)
    key = jax.random.PRNGKey(0)

    gc1 = GenerationConfig(max_new_tokens=12, temperature=0.0, stop_tokens=())
    want = np.asarray(generate(params, tokens, mask, key, config=config,
                               gen_config=gc1))
    for chunk in (4, 8, 16, 64):
        gcc = GenerationConfig(max_new_tokens=12, temperature=0.0,
                               stop_tokens=(), prefill_chunk=chunk)
        got = np.asarray(generate(params, tokens, mask, key, config=config,
                                  gen_config=gcc))
        np.testing.assert_array_equal(got, want, err_msg=f"chunk={chunk}")


def test_score_matches_manual_softmax():
    import numpy as np
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.engine import score
    from jax_llama_tpu.models import forward

    config = get_config(
        "tiny", vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=32,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 10
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (B, T)), jnp.int32
    )
    got = np.asarray(score(params, tokens, config=config))

    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    logits, _ = forward(params, tokens, pos, config)
    lp = np.asarray(jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32)))
    want = np.take_along_axis(lp, np.asarray(tokens)[:, 1:, None], axis=-1)[..., 0]
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert got.shape == (B, T - 1)
    # padded rows score 0
    mask = jnp.ones((B, T), bool).at[0, :3].set(False)
    got2 = np.asarray(score(params, tokens, mask, config=config))
    assert (got2[0, :3] == 0).all()


def test_prompt_containing_eos_is_not_masked():
    """The reference pads with eos and derives its mask as tokens != eos
    (reference generation.py:55-60), silently masking genuine eos tokens
    inside a prompt.  This framework takes an explicit mask, so an eos in
    the prompt participates in attention like any other token — outputs
    must differ from the same prompt with that position masked out."""
    import numpy as np
    from jax_llama_tpu import get_config, init_params
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config(
        "tiny", vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    eos = 2
    prompt = jnp.asarray([[9, eos, 13, 21, 40, 7]], jnp.int32)
    mask_full = jnp.ones((1, 6), bool)
    mask_holed = mask_full.at[0, 1].set(False)  # what the reference does
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    a = np.asarray(generate(params, prompt, mask_full, jax.random.PRNGKey(0),
                            config=config, gen_config=gc))
    b = np.asarray(generate(params, prompt, mask_holed, jax.random.PRNGKey(0),
                            config=config, gen_config=gc))
    assert not np.array_equal(a[:, 6:], b[:, 6:]), (
        "masking the eos position should change the continuation"
    )


def test_generate_beyond_max_seq_len_matches_larger_config():
    """Long-context decode: a cache longer than config.max_seq_len (the
    bench's 16k-context path) must behave exactly like a config whose
    max_seq_len covers the whole generation — RoPE tables are sized by
    the reachable positions (max(2*max_seq_len, cache.max_len)), so the
    rotation at every position is identical."""
    import numpy as np

    small = cfg_lib.tiny(max_seq_len=32)
    big = small.replace(max_seq_len=128)
    params = init_params(jax.random.PRNGKey(0), small)
    B, P, N = 2, 48, 16  # prompt alone exceeds small.max_seq_len
    rng = np.random.RandomState(3)
    prompt = jnp.asarray(rng.randint(1, small.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), bool)
    gc = GenerationConfig(
        max_new_tokens=N, temperature=0.0, stop_tokens=(),
        prefill_chunk=16,
    )
    got = np.asarray(generate(
        params, prompt, mask, jax.random.PRNGKey(0), config=small,
        gen_config=gc,
    ))
    want = np.asarray(generate(
        params, prompt, mask, jax.random.PRNGKey(0), config=big,
        gen_config=gc,
    ))
    np.testing.assert_array_equal(got, want)
