"""Full-model numerical parity against the reference implementation ITSELF.

The reference validates its Flax model against Meta's torch ``llama``
(/root/reference/jax_test.py:427-522: last-token logits within atol, greedy
string equality).  Meta's checkpoints aren't available here, so the
strongest independent oracle in this environment is the reference's own
``FlaxLLaMAForCausalLM`` (/root/reference/jax_llama/model.py:745): we load
IDENTICAL weights into both models through a param-mapping shim and require
fp32 logit agreement for plain forward, left-padded batches, cached decode,
and token-for-token greedy generation — plus an exact tree diff of the two
Meta-checkpoint converters over the same synthetic sharded checkpoint
(/root/reference/jax_llama/convert_weights.py:52-92).

The reference package is imported from /root/reference via a synthetic
package entry (its ``__init__`` pulls sentencepiece, which this image lacks
— we stub it; everything these tests exercise is flax/transformers only).
Tests skip if the reference tree is absent.
"""

import importlib
import sys
import types
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from jax_llama_tpu import get_config, init_cache, init_params
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.models import forward

REF_DIR = Path("/root/reference")

pytestmark = pytest.mark.skipif(
    not (REF_DIR / "jax_llama" / "model.py").exists(),
    reason="reference tree not available",
)


def _load_reference():
    """Import reference submodules without executing the package __init__
    (which requires sentencepiece)."""
    if "sentencepiece" not in sys.modules:
        try:
            importlib.import_module("sentencepiece")
        except ImportError:
            stub = types.ModuleType("sentencepiece")
            stub.SentencePieceProcessor = object
            # transformers probes availability via find_spec, which requires
            # a real-looking __spec__ on an already-imported module.
            stub.__spec__ = importlib.machinery.ModuleSpec(
                "sentencepiece", loader=None
            )
            sys.modules["sentencepiece"] = stub
    if "jax_llama" not in sys.modules:
        pkg = types.ModuleType("jax_llama")
        pkg.__path__ = [str(REF_DIR / "jax_llama")]
        sys.modules["jax_llama"] = pkg
    model = importlib.import_module("jax_llama.model")
    config = importlib.import_module("jax_llama.config")
    return model, config


# Small but non-degenerate: GQA (H != KVH), 3 layers, odd-ish vocab.
DIM, HEADS, KV_HEADS, LAYERS, VOCAB, FFN_MULT, MAX_LEN = 64, 4, 2, 3, 199, 32, 64


@pytest.fixture(scope="module")
def models():
    ref_model_mod, ref_config_mod = _load_reference()
    config = get_config(
        "tiny", vocab_size=VOCAB, dim=DIM, n_layers=LAYERS, n_heads=HEADS,
        n_kv_heads=KV_HEADS, multiple_of=FFN_MULT, max_seq_len=MAX_LEN,
        dtype="float32", param_dtype="float32",
    )
    ref_config = ref_config_mod.LLaMAConfig(
        vocab_size=VOCAB, hidden_size=DIM, intermediate_size=config.ffn_dim,
        num_hidden_layers=LAYERS, num_attention_heads=HEADS,
        num_key_value_heads=KV_HEADS, max_sequence_length=MAX_LEN,
        rms_norm_eps=config.rms_norm_eps, rope_theta=config.rope_theta,
    )
    ref = ref_model_mod.FlaxLLaMAForCausalLM(
        ref_config, input_shape=(1, 2), seed=0, dtype=jnp.float32,
        _do_init=False,
    )
    params = init_params(jax.random.PRNGKey(7), config)
    return ref, to_reference_params(params, config), params, config


def to_reference_params(params, config):
    """Map our stacked-layer pytree onto the reference's Flax param tree.

    Layout contract (reference model.py:105-180,302-341,602-744): Dense
    kernels are [in, out]; our fused per-layer qkv [KVH, G+2, D, hd]
    splits (models.llama.split_qkv) into the reference's separate
    [D, H*hd] / [D, KVH*hd] kernels; o [H, hd, D] flattens to [H*hd, D];
    gate_up[0]/gate_up[1]/down are w1/w3/w2; norms are 1-D 'kernel's.
    """
    from jax_llama_tpu.models import split_qkv

    D, H, KVH, hd = config.dim, config.n_heads, config.kv_heads, config.head_dim
    lp = params["layers"]
    f32 = lambda x: np.asarray(x, np.float32)
    h = {}
    for i in range(config.n_layers):
        q_i, k_i, v_i = split_qkv(lp["qkv"][i])
        h[str(i)] = {
            "attention": {
                "wq": {"kernel": f32(q_i).reshape(D, H * hd)},
                "wk": {"kernel": f32(k_i).reshape(D, KVH * hd)},
                "wv": {"kernel": f32(v_i).reshape(D, KVH * hd)},
                "wo": {"kernel": f32(lp["o"][i]).reshape(H * hd, D)},
            },
            "feed_forward": {
                "w1": {"kernel": f32(lp["gate_up"][i][0])},
                "w2": {"kernel": f32(lp["down"][i])},
                "w3": {"kernel": f32(lp["gate_up"][i][1])},
            },
            "attention_norm": {"kernel": f32(lp["attn_norm"][i])},
            "ffn_norm": {"kernel": f32(lp["mlp_norm"][i])},
        }
    return {
        "transformer": {
            "wte": {"embedding": f32(params["embed"]["embedding"])},
            "ln_f": {"kernel": f32(params["final_norm"])},
            "h": h,
        },
        "lm_head": {"kernel": f32(params["lm_head"])},
    }


def _assert_close(mine, ref, atol=1e-3, what=""):
    mine, ref = np.asarray(mine, np.float64), np.asarray(ref, np.float64)
    np.testing.assert_allclose(mine, ref, atol=atol, rtol=0, err_msg=what)


def test_plain_forward_logits_match(models):
    ref, ref_params, params, config = models
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, VOCAB, size=(2, 16)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))

    mine, _ = forward(params, tokens, positions, config)
    theirs = ref(tokens, params=ref_params).logits
    _assert_close(mine, theirs, what="plain forward")


def test_left_padded_batch_matches(models):
    ref, ref_params, params, config = models
    rng = np.random.RandomState(1)
    B, P = 3, 12
    lens = [12, 7, 4]
    tokens = np.zeros((B, P), np.int32)
    mask = np.zeros((B, P), bool)
    for b, L in enumerate(lens):
        tokens[b, P - L:] = rng.randint(1, VOCAB, size=L)
        mask[b, P - L:] = True

    # Reference convention (model.py:756-761): position_ids = cumsum - 1.
    att = jnp.asarray(mask, jnp.int32)
    ref_pos = jnp.cumsum(att, axis=-1) - 1
    theirs = ref(
        jnp.asarray(tokens), attention_mask=att, position_ids=ref_pos,
        params=ref_params,
    ).logits

    # Our convention: padding carries position -1 (mask derives from it).
    my_pos = jnp.where(jnp.asarray(mask), ref_pos, -1).astype(jnp.int32)
    mine, _ = forward(params, jnp.asarray(tokens), my_pos, config)

    # Compare only real positions: logits at padded slots are unspecified
    # (both models mask them out of every downstream attention).
    for b, L in enumerate(lens):
        _assert_close(
            mine[b, P - L:], theirs[b, P - L:], what=f"left-pad row {b}"
        )


def test_hidden_states_and_attentions_match_reference(models):
    """The aux output surface (forward(..., output_hidden_states=True,
    output_attentions=True)) reproduces the reference's exact collection
    points (model.py:580-581 per-block inputs, :663-666 final norm
    appended, :299 per-layer post-softmax weights) with shared weights —
    and requesting aux does not change the logits."""
    ref, ref_params, params, config = models
    rng = np.random.RandomState(4)
    tokens = jnp.asarray(rng.randint(0, VOCAB, size=(2, 12)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(12, dtype=jnp.int32), (2, 12))

    mine, _, aux = forward(
        params, tokens, positions, config,
        output_hidden_states=True, output_attentions=True,
    )
    theirs = ref(
        tokens, params=ref_params,
        output_hidden_states=True, output_attentions=True,
    )

    assert aux.hidden_states.shape == (LAYERS + 1, 2, 12, DIM)
    for i in range(LAYERS + 1):
        _assert_close(
            aux.hidden_states[i], theirs.hidden_states[i],
            what=f"hidden_states[{i}]",
        )
    _assert_close(
        aux.last_hidden_state, theirs.hidden_states[-1],
        what="last_hidden_state (base model without head)",
    )
    assert aux.attentions.shape == (LAYERS, 2, HEADS, 12, 12)
    for i in range(LAYERS):
        _assert_close(
            aux.attentions[i], theirs.attentions[i], what=f"attentions[{i}]"
        )

    plain, _ = forward(params, tokens, positions, config)
    _assert_close(mine, plain, what="logits unaffected by aux flags")


def test_cached_decode_matches_for_20_steps(models):
    ref, ref_params, params, config = models
    rng = np.random.RandomState(2)
    B, P, STEPS = 2, 8, 20
    prompt = jnp.asarray(rng.randint(0, VOCAB, size=(B, P)), jnp.int32)
    max_len = P + STEPS

    # Reference: Flax mutable-cache protocol (model.py:459-546).
    ref_cache = ref.init_cache(B, max_len)
    att = jnp.ones((B, max_len), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P))
    out = ref(prompt, attention_mask=att, position_ids=pos,
              params=ref_params, past_key_values=ref_cache)
    ref_logits = [np.asarray(out.logits[:, -1])]
    ref_cache = out.past_key_values

    # Ours: explicit KVCache pytree.
    cache = init_cache(config, B, max_len=max_len)
    mine, cache = forward(params, prompt, pos, config, cache=cache)
    my_logits = [np.asarray(mine[:, -1])]

    step_tok = prompt[:, -1:]
    for i in range(STEPS - 1):
        step_pos = jnp.full((B, 1), P + i, dtype=jnp.int32)
        out = ref(step_tok, attention_mask=att, position_ids=step_pos,
                  params=ref_params, past_key_values=ref_cache)
        ref_cache = out.past_key_values
        ref_logits.append(np.asarray(out.logits[:, -1]))

        lg, cache = forward(params, step_tok, step_pos, config, cache=cache)
        my_logits.append(np.asarray(lg[:, -1]))

        # Drive both with the same (reference-chosen) greedy next token so
        # any divergence is a numerics failure, not drift.
        step_tok = jnp.argmax(out.logits[:, -1], axis=-1)[:, None].astype(jnp.int32)

    for i, (m, r) in enumerate(zip(my_logits, ref_logits)):
        _assert_close(m, r, what=f"cached decode step {i}")


def test_greedy_generation_token_for_token(models):
    ref, ref_params, params, config = models
    rng = np.random.RandomState(3)
    B, P, NEW = 2, 6, 16
    prompt = jnp.asarray(rng.randint(1, VOCAB, size=(B, P)), jnp.int32)

    # Reference greedy loop over its cached decode path.
    ref_cache = ref.init_cache(B, P + NEW)
    att = jnp.ones((B, P + NEW), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P))
    out = ref(prompt, attention_mask=att, position_ids=pos,
              params=ref_params, past_key_values=ref_cache)
    ref_cache = out.past_key_values
    tok = jnp.argmax(out.logits[:, -1], axis=-1)[:, None].astype(jnp.int32)
    ref_tokens = [np.asarray(tok[:, 0])]
    for i in range(NEW - 1):
        out = ref(tok, attention_mask=att,
                  position_ids=jnp.full((B, 1), P + i, dtype=jnp.int32),
                  params=ref_params, past_key_values=ref_cache)
        ref_cache = out.past_key_values
        tok = jnp.argmax(out.logits[:, -1], axis=-1)[:, None].astype(jnp.int32)
        ref_tokens.append(np.asarray(tok[:, 0]))
    ref_tokens = np.stack(ref_tokens, axis=1)  # [B, NEW]

    # Our whole generation engine (jitted prefill + while_loop decode).
    got = generate(
        params, prompt, jnp.ones((B, P), bool), jax.random.PRNGKey(0),
        config=config,
        gen_config=GenerationConfig(max_new_tokens=NEW, temperature=0.0),
    )
    np.testing.assert_array_equal(
        np.asarray(got[:, P:]), ref_tokens,
        err_msg="greedy generation diverged from the reference model",
    )


# ---------------------------------------------------------------------------
# Converter cross-check: both converters over one synthetic Meta checkpoint
# ---------------------------------------------------------------------------

def _write_synthetic_meta_checkpoint(tmpdir, n_shards=2):
    """Emit a sharded Llama-2-style Meta checkpoint (Megatron splits:
    wq/wk/wv/w1/w3/output on rows, wo/w2/embedding on columns)."""
    import json

    import torch

    D, H, KVH, L, V = DIM, HEADS, KV_HEADS, LAYERS, VOCAB + 1  # even vocab
    hd = D // H
    FF = 2 * (D * 4) // 3
    FF = FFN_MULT * ((FF + FFN_MULT - 1) // FFN_MULT)
    rng = np.random.RandomState(11)
    t = lambda *s: torch.from_numpy(rng.randn(*s).astype(np.float32))

    full = {"tok_embeddings.weight": t(V, D), "norm.weight": t(D),
            "output.weight": t(V, D)}
    for i in range(L):
        p = f"layers.{i}."
        full[p + "attention.wq.weight"] = t(H * hd, D)
        full[p + "attention.wk.weight"] = t(KVH * hd, D)
        full[p + "attention.wv.weight"] = t(KVH * hd, D)
        full[p + "attention.wo.weight"] = t(D, H * hd)
        full[p + "feed_forward.w1.weight"] = t(FF, D)
        full[p + "feed_forward.w2.weight"] = t(D, FF)
        full[p + "feed_forward.w3.weight"] = t(FF, D)
        full[p + "attention_norm.weight"] = t(D)
        full[p + "ffn_norm.weight"] = t(D)

    col_split = {"tok_embeddings.weight": 1, "attention.wo.weight": 1,
                 "feed_forward.w2.weight": 1}
    for s in range(n_shards):
        shard = {}
        for k, v_ in full.items():
            axis = next(
                (ax for suf, ax in col_split.items() if k.endswith(suf)), 0
            )
            if v_.ndim == 1:
                shard[k] = v_.clone()  # replicated
            else:
                shard[k] = torch.chunk(v_, n_shards, dim=axis)[s].clone()
        torch.save(shard, f"{tmpdir}/consolidated.{s:02d}.pth")
    with open(f"{tmpdir}/params.json", "w") as f:
        json.dump({"dim": D, "n_layers": L, "n_heads": H, "n_kv_heads": KVH,
                   "multiple_of": FFN_MULT, "norm_eps": 1e-5}, f)
    return V


def test_converters_agree_on_synthetic_checkpoint(tmp_path):
    _load_reference()
    ref_convert = importlib.import_module("jax_llama.convert_weights")
    from jax_llama_tpu.convert.meta import convert_meta_checkpoint

    V = _write_synthetic_meta_checkpoint(tmp_path)

    class FakeTok:
        def __len__(self):
            return V

    ref_tree, ref_cfg = ref_convert.convert_llama_weights(
        str(tmp_path), FakeTok(), max_seq_len=MAX_LEN,
    )
    mine, my_cfg = convert_meta_checkpoint(
        str(tmp_path), vocab_size=V, max_seq_len=MAX_LEN, dtype="float32",
    )
    assert my_cfg.ffn_dim == ref_cfg.intermediate_size
    assert my_cfg.n_kv_heads == ref_cfg.num_key_value_heads

    mapped = to_reference_params(mine, my_cfg)
    ref_flat = jax.tree_util.tree_flatten_with_path(ref_tree)[0]
    my_flat = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_flatten_with_path(mapped)[0]
    )
    assert len(ref_flat) == len(my_flat)
    for key, ref_leaf in ref_flat:
        ks = jax.tree_util.keystr(key)
        np.testing.assert_array_equal(
            my_flat[ks], np.asarray(ref_leaf),
            err_msg=f"converter mismatch at {ks}",
        )
