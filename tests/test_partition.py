"""Sharding tests on the 8-virtual-device CPU mesh — the multi-device
coverage the reference lacks entirely (SURVEY.md §4: JAX always runs
single-process in the reference's harness)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import config as cfg_lib
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.models import forward, init_params
from jax_llama_tpu.parallel import (
    make_mesh,
    param_partition_specs,
    shard_params,
    use_mesh,
    validate_tp,
)

CFG = cfg_lib.tiny(max_seq_len=64)  # dim=32 H=4 KVH=2 vocab=256 ffn=96


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _forward_ref(params, tokens, positions):
    logits, _ = forward(params, tokens, positions, CFG)
    return np.asarray(logits)


def test_spec_tree_mirrors_param_tree(params):
    specs = param_partition_specs(CFG)
    jax.tree.map(lambda p, s: None, params, specs)  # raises on mismatch


def test_specs_cover_fsdp_variant(params):
    specs = param_partition_specs(CFG, fsdp=True)
    jax.tree.map(lambda p, s: None, params, specs)


def test_tp_sharded_leaves(params):
    mesh = make_mesh(tensor=2, data=4)
    sharded = shard_params(params, mesh, CFG)
    # [L, KVH, G+2, D, hd] sharded on KVH over tensor=2
    qkv = sharded["layers"]["qkv"]
    G = CFG.n_heads // CFG.kv_heads
    shard_shapes = {s.data.shape for s in qkv.addressable_shards}
    assert shard_shapes == {
        (CFG.n_layers, CFG.kv_heads // 2, G + 2, CFG.dim, CFG.head_dim)
    }


@pytest.mark.parametrize("axes", [dict(tensor=2, data=4),
                                  dict(tensor=2, fsdp=2, data=2),
                                  dict(fsdp=4, data=2)])
def test_sharded_forward_matches_single_device(params, axes):
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (2, 10)))
    positions = jnp.tile(jnp.arange(10)[None, :], (2, 1))
    want = _forward_ref(params, tokens, positions)

    mesh = make_mesh(**axes)
    sharded = shard_params(params, mesh, CFG, fsdp="fsdp" in axes)
    with use_mesh(mesh):
        got = np.asarray(
            jax.jit(lambda p, t, pos: forward(p, t, pos, CFG)[0])(
                sharded, tokens, positions
            )
        )
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_generate_on_mesh_matches_single_device(params):
    prompt = jnp.asarray([[5, 17, 200, 3]], dtype=jnp.int32)
    mask = jnp.ones((1, 4), bool)
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0)
    want = np.asarray(generate(params, prompt, mask, jax.random.PRNGKey(0),
                               config=CFG, gen_config=gc))
    mesh = make_mesh(tensor=2, data=4)
    sharded = shard_params(params, mesh, CFG)
    got = np.asarray(generate(sharded, prompt, mask, jax.random.PRNGKey(0),
                              config=CFG, gen_config=gc, mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_validate_tp_rejects_bad_kv_split():
    mesh = make_mesh(tensor=4, data=2)  # KVH=2 not divisible by 4
    with pytest.raises(ValueError, match="n_kv_heads"):
        validate_tp(CFG, mesh)


def test_batch_sharded_over_data_axis(params):
    mesh = make_mesh(data=4, tensor=2)
    sharded = shard_params(params, mesh, CFG)
    tokens = jnp.asarray(np.random.randint(0, CFG.vocab_size, (8, 6)))
    positions = jnp.tile(jnp.arange(6)[None, :], (8, 1))
    with use_mesh(mesh):
        logits = jax.jit(lambda p, t, pos: forward(p, t, pos, CFG)[0])(
            sharded, tokens, positions
        )
    want = _forward_ref(params, tokens, positions)
    np.testing.assert_allclose(np.asarray(logits), want, atol=1e-4, rtol=1e-4)
