"""Ring attention (sequence/context parallelism) on the 8-device CPU mesh.

SURVEY.md §4's implication: multi-device paths must be testable without
hardware.  Parity target is the dense sdpa path, which is itself
oracle-checked.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from jax_llama_tpu import get_config, init_params, make_mesh
from jax_llama_tpu.models import forward
from jax_llama_tpu.ops import attention_bias, sdpa
from jax_llama_tpu.parallel import ring_sdpa, shard_params, use_mesh
from jax_llama_tpu.parallel.ring import ring_attention


def _dense(q, k, v, q_pos, kv_pos):
    bias = attention_bias(
        jnp.asarray(q_pos), jnp.asarray(kv_pos), jnp.asarray(kv_pos) >= 0
    )
    return np.asarray(
        sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias)
    )


def test_ring_sdpa_matches_dense_seq4():
    B, T, H, KVH, D = 2, 32, 4, 2, 8
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, KVH, D).astype(np.float32)
    v = np.random.randn(B, T, KVH, D).astype(np.float32)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))

    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    with use_mesh(mesh):
        got = np.asarray(
            ring_sdpa(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(pos), jnp.asarray(pos),
            )
        )
    want = _dense(q, k, v, pos, pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_sdpa_with_padding_positions():
    # Left-padded rows: pad slots carry kv_pos=-1 and must never be attended,
    # no matter which device's shard they land on.
    B, T, H, KVH, D = 2, 16, 2, 2, 8
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, KVH, D).astype(np.float32)
    v = np.random.randn(B, T, KVH, D).astype(np.float32)
    npad = 5
    q_pos = np.tile(
        np.concatenate([np.zeros(npad), np.arange(T - npad)]).astype(np.int32),
        (B, 1),
    )
    kv_pos = q_pos.copy()
    kv_pos[:, :npad] = -1

    mesh = make_mesh(seq=8, devices=jax.devices()[:8])
    with use_mesh(mesh):
        got = np.asarray(
            ring_sdpa(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(q_pos), jnp.asarray(kv_pos),
            )
        )
    want = _dense(q, k, v, q_pos, kv_pos)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_sdpa_no_mesh_fallback():
    B, T, H, D = 1, 8, 2, 4
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, H, D).astype(np.float32)
    v = np.random.randn(B, T, H, D).astype(np.float32)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    got = np.asarray(
        ring_sdpa(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(got, _dense(q, k, v, pos, pos), atol=1e-5)


def test_model_forward_ring_matches_single_device():
    # Full model under a data×seq×tensor mesh with ring attention vs the
    # unsharded XLA path.
    config = get_config("tiny", dtype="float32")
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 32
    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ref_logits, _ = forward(params, tokens, positions, config)

    mesh = make_mesh(data=2, seq=2, tensor=2, devices=jax.devices()[:8])
    ring_config = config.replace(attn_impl="ring")
    sharded = shard_params(params, mesh, ring_config)
    with use_mesh(mesh):
        got, _ = jax.jit(
            lambda p, t, pos: forward(p, t, pos, ring_config)
        )(sharded, tokens, positions)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_logits), atol=2e-4, rtol=1e-4
    )


def test_ring_train_step_matches_single_device():
    from jax_llama_tpu.train import init_train_state, make_optimizer, train_step

    opt = make_optimizer(learning_rate=1e-3)
    config = get_config("tiny", dtype="float32")
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, config.vocab_size, (4, 16))
    )
    state = init_train_state(init_params(jax.random.PRNGKey(0), config), opt)
    _, loss_single = train_step(state, tokens, config, opt)

    mesh = make_mesh(data=2, seq=2, tensor=2, devices=jax.devices()[:8])
    ring_config = config.replace(attn_impl="ring")
    sharded = shard_params(init_params(jax.random.PRNGKey(0), config), mesh, ring_config)
    sstate = init_train_state(sharded, opt)
    sstate, loss_ring = train_step(sstate, tokens, ring_config, opt, mesh=mesh)
    np.testing.assert_allclose(float(loss_ring), float(loss_single), rtol=1e-5)


def test_ring_decode_over_cache_refuses_seq_mesh():
    from jax_llama_tpu.models import init_cache

    config = get_config("tiny", attn_impl="ring")
    params = init_params(jax.random.PRNGKey(0), config)
    tokens = jnp.zeros((2, 4), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (2, 4))
    cache = init_cache(config, 2, max_len=8)
    mesh = make_mesh(seq=8, devices=jax.devices()[:8])
    with use_mesh(mesh):
        with pytest.raises(NotImplementedError, match="seq > 1"):
            forward(params, tokens, positions, config, cache=cache)
