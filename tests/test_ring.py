"""Ring attention (sequence/context parallelism) on the 8-device CPU mesh.

SURVEY.md §4's implication: multi-device paths must be testable without
hardware.  Parity target is the dense sdpa path, which is itself
oracle-checked.
"""

import numpy as np
import jax
import jax.numpy as jnp

from jax_llama_tpu import get_config, init_params, make_mesh
from jax_llama_tpu.models import forward
from jax_llama_tpu.ops import attention_bias, sdpa
from jax_llama_tpu.parallel import ring_sdpa, shard_params, use_mesh
from jax_llama_tpu.parallel.ring import ring_attention


def _dense(q, k, v, q_pos, kv_pos):
    bias = attention_bias(
        jnp.asarray(q_pos), jnp.asarray(kv_pos), jnp.asarray(kv_pos) >= 0
    )
    return np.asarray(
        sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias)
    )


def test_ring_sdpa_matches_dense_seq4():
    B, T, H, KVH, D = 2, 32, 4, 2, 8
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, KVH, D).astype(np.float32)
    v = np.random.randn(B, T, KVH, D).astype(np.float32)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))

    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    with use_mesh(mesh):
        got = np.asarray(
            ring_sdpa(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(pos), jnp.asarray(pos),
            )
        )
    want = _dense(q, k, v, pos, pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_sdpa_with_padding_positions():
    # Left-padded rows: pad slots carry kv_pos=-1 and must never be attended,
    # no matter which device's shard they land on.
    B, T, H, KVH, D = 2, 16, 2, 2, 8
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, KVH, D).astype(np.float32)
    v = np.random.randn(B, T, KVH, D).astype(np.float32)
    npad = 5
    q_pos = np.tile(
        np.concatenate([np.zeros(npad), np.arange(T - npad)]).astype(np.int32),
        (B, 1),
    )
    kv_pos = q_pos.copy()
    kv_pos[:, :npad] = -1

    mesh = make_mesh(seq=8, devices=jax.devices()[:8])
    with use_mesh(mesh):
        got = np.asarray(
            ring_sdpa(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                jnp.asarray(q_pos), jnp.asarray(kv_pos),
            )
        )
    want = _dense(q, k, v, q_pos, kv_pos)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_sdpa_no_mesh_fallback():
    B, T, H, D = 1, 8, 2, 4
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, H, D).astype(np.float32)
    v = np.random.randn(B, T, H, D).astype(np.float32)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    got = np.asarray(
        ring_sdpa(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos),
        )
    )
    np.testing.assert_allclose(got, _dense(q, k, v, pos, pos), atol=1e-5)


def test_model_forward_ring_matches_single_device():
    # Full model under a data×seq×tensor mesh with ring attention vs the
    # unsharded XLA path.
    config = get_config("tiny", dtype="float32")
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 32
    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ref_logits, _ = forward(params, tokens, positions, config)

    mesh = make_mesh(data=2, seq=2, tensor=2, devices=jax.devices()[:8])
    ring_config = config.replace(attn_impl="ring")
    sharded = shard_params(params, mesh, ring_config)
    with use_mesh(mesh):
        got, _ = jax.jit(
            lambda p, t, pos: forward(p, t, pos, ring_config)
        )(sharded, tokens, positions)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_logits), atol=2e-4, rtol=1e-4
    )


def test_ring_train_step_matches_single_device():
    from jax_llama_tpu.train import init_train_state, make_optimizer, train_step

    opt = make_optimizer(learning_rate=1e-3)
    config = get_config("tiny", dtype="float32")
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, config.vocab_size, (4, 16))
    )
    state = init_train_state(init_params(jax.random.PRNGKey(0), config), opt)
    _, loss_single = train_step(state, tokens, config, opt)

    mesh = make_mesh(data=2, seq=2, tensor=2, devices=jax.devices()[:8])
    ring_config = config.replace(attn_impl="ring")
    sharded = shard_params(init_params(jax.random.PRNGKey(0), config), mesh, ring_config)
    sstate = init_train_state(sharded, opt)
    sstate, loss_ring = train_step(sstate, tokens, ring_config, opt, mesh=mesh)
    np.testing.assert_allclose(float(loss_ring), float(loss_single), rtol=1e-5)


def test_ring_cached_decode_matches_single_device():
    """Seq-sharded cached decode (ring_decode): prefill + stepwise decode
    over a cache sharded along S on a seq=4 mesh must reproduce the
    single-device xla decode logits exactly (fp32 CPU)."""
    from jax_llama_tpu.models import init_cache

    config = get_config("tiny", dtype="float32", max_seq_len=16)
    params = init_params(jax.random.PRNGKey(0), config)
    B, P, STEPS = 2, 8, 4
    rng = np.random.RandomState(5)
    prompt = jnp.asarray(rng.randint(0, config.vocab_size, (B, P)), jnp.int32)
    ppos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P))
    steps = jnp.asarray(rng.randint(0, config.vocab_size, (B, STEPS)), jnp.int32)

    # Single-device xla reference.
    ref_cache = init_cache(config, B, max_len=16)
    ref_logits = []
    lg, ref_cache = forward(params, prompt, ppos, config, cache=ref_cache)
    ref_logits.append(np.asarray(lg[:, -1]))
    for i in range(STEPS):
        lg, ref_cache = forward(
            params, steps[:, i:i + 1],
            jnp.full((B, 1), P + i, jnp.int32), config, cache=ref_cache,
        )
        ref_logits.append(np.asarray(lg[:, 0]))

    # Seq-sharded ring decode (cache max_len 16 % seq 4 == 0).
    ring_config = config.replace(attn_impl="ring")
    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    sharded = shard_params(params, mesh, ring_config)
    with use_mesh(mesh):
        cache = init_cache(ring_config, B, max_len=16)
        step = jax.jit(
            lambda p, t, pos, c: forward(p, t, pos, ring_config, cache=c)
        )
        got_logits = []
        lg, cache = step(sharded, prompt, ppos, cache)
        got_logits.append(np.asarray(lg[:, -1]))
        for i in range(STEPS):
            lg, cache = step(
                sharded, steps[:, i:i + 1],
                jnp.full((B, 1), P + i, jnp.int32), cache,
            )
            got_logits.append(np.asarray(lg[:, 0]))

    for j, (g, r) in enumerate(zip(got_logits, ref_logits)):
        np.testing.assert_allclose(g, r, atol=2e-4, rtol=1e-4, err_msg=f"step {j}")


def test_ring_cached_generate_matches_single_device():
    """engine.generate with a seq-sharded cache: token-identical to the
    unsharded xla generate (the BASELINE config-4 long-context story —
    generation context bounded by the mesh's combined HBM)."""
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config("tiny", dtype="float32", max_seq_len=16)
    params = init_params(jax.random.PRNGKey(0), config)
    B, P, N = 2, 8, 8  # cache = P + N = 16, divisible by seq=4
    rng = np.random.RandomState(7)
    prompt = jnp.asarray(rng.randint(1, config.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), bool)
    gc = GenerationConfig(max_new_tokens=N, temperature=0.0, stop_tokens=())
    want = np.asarray(generate(
        params, prompt, mask, jax.random.PRNGKey(0), config=config,
        gen_config=gc,
    ))

    ring_config = config.replace(attn_impl="ring")
    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    sharded = shard_params(params, mesh, ring_config)
    got = np.asarray(generate(
        sharded, prompt, mask, jax.random.PRNGKey(0), config=ring_config,
        gen_config=gc, mesh=mesh,
    ))
    np.testing.assert_array_equal(got, want)


def test_ring_forward_no_quadratic_memory_32k():
    """The chunked inner loop's point: no [T_local, S_local] intermediate
    in the 32k ring forward jaxpr — peak attention memory is
    O(T_local · RING_CHUNK) per device."""
    from jax_llama_tpu.parallel.ring import RING_CHUNK, ring_attention

    B, S, H, D = 1, 32768, 1, 64
    n_shards = 8
    S_local = S // n_shards  # 4096 per device

    def fwd(q, k, v):
        pos = jnp.broadcast_to(jnp.arange(S_local, dtype=jnp.int32), (B, S_local))
        return ring_attention(
            q, k, v, pos, pos, axis_name="seq", axis_size=1
        ).sum()

    sds_q = jax.ShapeDtypeStruct((B, S_local, H, D), jnp.float32)
    sds_kv = jax.ShapeDtypeStruct((B, S_local, H, D), jnp.float32)
    # axis_size=1 keeps the jaxpr collective-free (per-device body only);
    # the accumulation structure is identical per rotation.
    jaxpr = jax.make_jaxpr(fwd)(sds_q, sds_kv, sds_kv)

    limit = B * H * S_local * max(RING_CHUNK, D) * 2
    def walk(jpr):
        for eqn in jpr.eqns:
            for var in eqn.outvars:
                size = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                assert size <= limit, (eqn.primitive.name, var.aval.shape)
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)
    walk(jaxpr.jaxpr)


def test_ring_chunked_accumulate_matches_unchunked():
    """Chunk size must not change the math: fold a shard with chunk sizes
    that do and don't divide S, against a direct dense fold."""
    from jax_llama_tpu.parallel.ring import _accumulate, _fold_chunk

    rng = np.random.RandomState(9)
    B, H, KVH, T, S, d = 2, 4, 2, 8, 192, 16
    qt = jnp.asarray(rng.randn(B, H, T, d), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, KVH, d), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, KVH, d), jnp.float32)
    q_pos = jnp.asarray(rng.randint(0, S, (B, T)), jnp.int32)
    kv_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    from jax_llama_tpu.ops.flash_attention import MASK_VALUE

    m0 = jnp.full((B, H, T), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    a0 = jnp.zeros((B, H, T, d), jnp.float32)
    want = _fold_chunk(qt, q_pos, k, v, kv_pos, m0, l0, a0, scale=0.25)
    for chunk in (64, 80, 192, 512):
        got = _accumulate(
            qt, q_pos, k, v, kv_pos, m0, l0, a0, scale=0.25, chunk=chunk
        )
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=1e-5, rtol=1e-5
            )


def test_ring_cached_decode_int8_kv():
    """int8 KV + seq-sharded decode: the payload and its dequant scales
    shard along S together and fold per shard — logits must match the
    single-device int8 xla decode (both paths quantize identically, so
    fp32 CPU agreement is exact up to reduction order)."""
    from jax_llama_tpu.models import init_cache

    config = get_config(
        "tiny", dtype="float32", max_seq_len=16, kv_cache_dtype="int8"
    )
    params = init_params(jax.random.PRNGKey(0), config)
    B, P, STEPS = 2, 8, 4
    rng = np.random.RandomState(11)
    prompt = jnp.asarray(rng.randint(0, config.vocab_size, (B, P)), jnp.int32)
    ppos = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P))
    steps = jnp.asarray(rng.randint(0, config.vocab_size, (B, STEPS)), jnp.int32)

    ref_cache = init_cache(config, B, max_len=16)
    assert ref_cache.quantized
    ref_logits = []
    lg, ref_cache = forward(params, prompt, ppos, config, cache=ref_cache)
    ref_logits.append(np.asarray(lg[:, -1]))
    for i in range(STEPS):
        lg, ref_cache = forward(
            params, steps[:, i:i + 1],
            jnp.full((B, 1), P + i, jnp.int32), config, cache=ref_cache,
        )
        ref_logits.append(np.asarray(lg[:, 0]))

    ring_config = config.replace(attn_impl="ring")
    mesh = make_mesh(data=2, seq=4, devices=jax.devices()[:8])
    sharded = shard_params(params, mesh, ring_config)
    with use_mesh(mesh):
        cache = init_cache(ring_config, B, max_len=16)
        step = jax.jit(
            lambda p, t, pos, c: forward(p, t, pos, ring_config, cache=c)
        )
        got_logits = []
        lg, cache = step(sharded, prompt, ppos, cache)
        got_logits.append(np.asarray(lg[:, -1]))
        for i in range(STEPS):
            lg, cache = step(
                sharded, steps[:, i:i + 1],
                jnp.full((B, 1), P + i, jnp.int32), cache,
            )
            got_logits.append(np.asarray(lg[:, 0]))

    for j, (g, r) in enumerate(zip(got_logits, ref_logits)):
        np.testing.assert_allclose(
            g, r, atol=2e-4, rtol=1e-4, err_msg=f"step {j}"
        )


def test_ring_dropout_matches_dense_with_extracted_mask():
    """attn_pdrop on the ring path: the position-keyed counter-hash mask
    (ring.dropout_keep) must reproduce EXACTLY a dense attention whose
    post-softmax weights are inverted-dropout masked with the same keep
    matrix — on a (data=2, seq=2) mesh (batch sharding exercises the
    global batch offsets), and invariantly across kv-chunk sizes."""
    from jax_llama_tpu.ops.attention import repeat_kv
    from jax_llama_tpu.parallel.ring import (
        _accumulate, dropout_base, dropout_keep,
    )
    from jax_llama_tpu.ops.flash_attention import MASK_VALUE

    B, T, H, KVH, D = 2, 32, 4, 2, 8
    rate = 0.3
    rng = np.random.RandomState(3)
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, KVH, D).astype(np.float32)
    v = rng.randn(B, T, KVH, D).astype(np.float32)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))

    def dense_with_mask(seed):
        base = np.asarray(dropout_base(seed, B, H, 0, 0))
        keep = np.asarray(dropout_keep(
            jnp.asarray(base), jnp.asarray(pos), jnp.asarray(pos), rate
        ))  # [B, H, T, T]
        kr = np.asarray(repeat_kv(jnp.asarray(k), H // KVH))
        vr = np.asarray(repeat_kv(jnp.asarray(v), H // KVH))
        s = np.einsum("bthd,bshd->bhts", q, kr) / np.sqrt(D)
        causal = pos[:, None, None, :] <= pos[:, None, :, None]
        s = np.where(causal, s, -1e30)
        w = np.exp(s - s.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        w_drop = np.where(keep, w / (1.0 - rate), 0.0)
        return np.einsum("bhts,bshd->bthd", w_drop, vr)

    # Mesh path: ring_sdpa derives its 64-bit (two-word) seed from the
    # rng key; mirror the derivation so the dense oracle shares it.
    key = jax.random.PRNGKey(77)
    derived = np.asarray(jax.random.bits(key, (2,), "uint32"))
    mesh = make_mesh(data=2, seq=2, devices=jax.devices()[:4])
    with use_mesh(mesh):
        got = np.asarray(ring_sdpa(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos),
            dropout_rng=key, dropout_rate=rate,
        ))
    np.testing.assert_allclose(got, dense_with_mask(derived),
                               atol=1e-5, rtol=1e-5)

    # Direct body, no mesh: chunk-size invariance (the mask keys on
    # absolute positions, not chunk indices) + match the same oracle.
    seed = 1234
    base = dropout_base(np.uint32(seed), B, H, 0, 0)
    qt = jnp.swapaxes(jnp.asarray(q), 1, 2)
    outs = []
    for chunk in (8, 16, 32):
        m0 = jnp.full((B, H, T), MASK_VALUE, jnp.float32)
        l0 = jnp.zeros((B, H, T), jnp.float32)
        a0 = jnp.zeros((B, H, T, D), jnp.float32)
        m, l, acc = _accumulate(
            qt, jnp.asarray(pos), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), m0, l0, a0, scale=1.0 / np.sqrt(D),
            chunk=chunk, dropout_rate=rate, drop_base=base,
        )
        outs.append(np.asarray(
            jnp.swapaxes(acc / l[..., None], 1, 2)
        ))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)
    np.testing.assert_allclose(outs[0], outs[2], atol=1e-6)
    np.testing.assert_allclose(outs[0], dense_with_mask(seed),
                               atol=1e-5, rtol=1e-5)


def test_ring_dropout_gradients_and_model_forward():
    """Gradients flow through the masked ring accumulation (the mask is a
    constant wrt inputs; jax.checkpoint rebuilds it bit-identically), and
    the model-level composition — forward(attn_impl='ring',
    attn_pdrop > 0) on a seq=2 mesh under jit — runs, is deterministic
    per key, distinct across keys, and finite."""
    from jax_llama_tpu.ops.attention import repeat_kv
    from jax_llama_tpu.parallel.ring import (
        dropout_base, dropout_keep, ring_attention,
    )

    B, T, H, KVH, D = 1, 16, 2, 2, 8
    rate, seed = 0.25, 99
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, KVH, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, T, KVH, D), jnp.float32)
    pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))

    def ring_loss(q, k, v):
        out = ring_attention(
            q, k, v, pos, pos, axis_size=1,
            dropout_rate=rate, dropout_seed=np.uint32(seed),
        )
        return jnp.sum(out * out)

    def dense_loss(q, k, v):
        base = dropout_base(np.uint32(seed), B, H, 0, 0)
        keep = dropout_keep(base, pos, pos, rate)
        kr = repeat_kv(k, H // KVH)
        vr = repeat_kv(v, H // KVH)
        s = jnp.einsum("bthd,bshd->bhts", q, kr) / np.sqrt(D)
        causal = pos[:, None, None, :] <= pos[:, None, :, None]
        s = jnp.where(causal, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        w = jnp.where(keep, w / (1.0 - rate), 0.0)
        out = jnp.einsum("bhts,bshd->bthd", w, vr)
        return jnp.sum(out * out)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        assert np.isfinite(np.asarray(gr)).all(), name
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=1e-5, rtol=1e-5,
            err_msg=f"d{name}",
        )

    # Model-level: the former refusal site now runs on a seq>=2 mesh.
    from jax_llama_tpu import config as cfg_lib
    from jax_llama_tpu.parallel import shard_params

    cfg = cfg_lib.tiny(max_seq_len=32, attn_impl="ring", attn_pdrop=0.4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(seq=2, devices=jax.devices()[:2])
    sp = shard_params(params, mesh, cfg)
    toks = jnp.asarray([list(range(1, 17))])
    p16 = jnp.arange(16)[None, :]

    @jax.jit
    def run(p, t, q, rng):
        with use_mesh(mesh):
            return forward(p, t, q, cfg, dropout_rng=rng)[0]

    la = run(sp, toks, p16, jax.random.PRNGKey(0))
    la2 = run(sp, toks, p16, jax.random.PRNGKey(0))
    lb = run(sp, toks, p16, jax.random.PRNGKey(1))
    l0 = run(sp, toks, p16, None)
    assert np.isfinite(np.asarray(la, np.float32)).all()
    np.testing.assert_array_equal(np.asarray(la), np.asarray(la2))
    assert np.abs(np.asarray(la) - np.asarray(lb)).max() > 0
    assert np.abs(np.asarray(la) - np.asarray(l0)).max() > 0
