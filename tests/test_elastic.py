"""Elastic fleet (router.FleetController): the autoscaler's
hysteresis decision machine, drain-by-migration scale-down (zero
dropped sessions, token-identical to the 1-replica oracle),
zero-downtime rollouts with the per-rung canary gate + auto-rollback,
and the no-capacity regression pin on ``migrate_chain``'s
residency-gated demote.

The invariants pinned here:
  * no scale action without a recorded, signal-carrying decision
    (``/debug/decisions?kind=scale`` explains every one of them);
  * scale-down never kills a replica the health sentinel can't
    explain (non-healthy verdict -> deferred, not destroyed);
  * a drain migrates EVERY live session's chain to a survivor and
    re-pins its routing record — revisits stream token-identically
    from the new home;
  * a rollout rung whose canary gate fails auto-rolls back and the
    fleet keeps serving (old weights) with nobody dropped;
  * a no-capacity import leaves the source's HBM chain fully intact
    (the demote is gated on destination residency, not on export).
"""

import threading
import time

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.obs import DecisionLog
from jax_llama_tpu.router import FleetController, _Replica
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher
from jax_llama_tpu.tokenizers.bytes import ByteTokenizer

from test_cache_routing import (  # shared tiny-model geometry + fleet
    CFG, OTHER, REVISIT, SESSION, _mk_batcher, _mk_fleet, _post,
    _serve_direct,
)

pytestmark = pytest.mark.mesh_serving

OTHER_REVISIT = OTHER + "ere"  # stays inside the max_len=64 geometry


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


# ---------------------------------------------------------------------------
# Host-only units: the hysteresis decision machine + sentinel gate
# ---------------------------------------------------------------------------

class _StubSentinel:
    def __init__(self):
        self.verdicts = {}

    def verdict(self, i):
        return self.verdicts.get(i, "healthy")


class _StubRouter:
    """Just enough router surface for the controller's decision units
    (no HTTP, no servers): a replica table with settable health
    scrapes, a real DecisionLog, a settable sentinel."""

    def __init__(self, n=2, n_slots=2):
        self._lock = threading.Lock()
        self._replicas = []
        for i in range(n):
            rep = _Replica(index=i, host="127.0.0.1", port=0)
            rep.last_health = {
                "replica": {"n_slots": n_slots}, "overload": {},
            }
            self._replicas.append(rep)
        self.sentinel = _StubSentinel()
        self.decisions = DecisionLog()
        self.fault_injector = None
        self.health_interval_s = 0.0
        self.handoff_timeout_s = 5.0
        self.controller = None

    def attach_controller(self, controller):
        self.controller = controller

    def _occupancy_locked(self, rep):
        slots = int((rep.last_health.get("replica") or {})
                    .get("n_slots") or 0)
        if slots <= 0:
            return float(rep.inflight)
        return rep.inflight / slots

    def set_overload(self, i, **kw):
        self._replicas[i].last_health["overload"].update(kw)


def _scale_decisions(router, **match):
    return [
        ev for ev in router.decisions.json(n=64, kind="scale")["decisions"]
        if all(ev.get(k) == v for k, v in match.items())
    ]


def test_autoscaler_steady_holds_without_decisions():
    r = _StubRouter()
    for i in range(2):
        r.set_overload(i, interactive_attainment=1.0,
                       queue_wait_ms_p90=1.0)
        r._replicas[i].inflight = 1  # occupancy 0.5: not calm
    ctrl = FleetController(r, min_replicas=1, max_replicas=4)
    out = ctrl.tick()
    assert out["action"] == "hold" and out["reason"] == "steady"
    assert r.decisions.json(kind="scale")["decisions"] == []
    sig = out["signals"]
    assert sig["replicas_active"] == 2
    assert sig["attainment_min"] == 1.0
    assert sig["occupancy_max"] == 0.5


def test_autoscaler_pressure_dwell_then_deferral_is_recorded():
    """Attainment pressure must SUSTAIN for dwell_s before acting;
    once it would act, a missing replica_factory is a recorded
    deferral — the decision log explains the non-action."""
    r = _StubRouter()
    r.set_overload(0, interactive_attainment=0.5)
    r.set_overload(1, interactive_attainment=1.0)
    ctrl = FleetController(r, min_replicas=1, max_replicas=4,
                           dwell_s=60.0)
    assert ctrl.tick()["reason"] == "dwell"
    ctrl2 = FleetController(r, min_replicas=1, max_replicas=4,
                            dwell_s=0.0)
    out = ctrl2.tick()
    assert out["reason"] == "no-replica-factory"
    evs = _scale_decisions(r, action="deferred",
                           reason="no-replica-factory")
    assert evs and evs[-1]["signals"]["attainment_min"] == 0.5
    assert ctrl2.metrics_snapshot()["scale_events"]["deferred"] == 1


def test_autoscaler_queue_wait_pressure_and_max_gate():
    r = _StubRouter()
    r.set_overload(0, queue_wait_ms_p90=900.0)
    ctrl = FleetController(
        r, replica_factory=lambda i: "127.0.0.1:1",
        min_replicas=1, max_replicas=2, queue_wait_high_ms=500.0,
    )
    out = ctrl.tick()
    assert out["reason"] == "at-max-replicas"
    assert _scale_decisions(r, action="deferred",
                            reason="at-max-replicas")


def test_autoscaler_calm_scaledown_min_gate_and_cooldown():
    r = _StubRouter(n=2)
    for i in range(2):
        r.set_overload(i, interactive_attainment=1.0,
                       queue_wait_ms_p90=0.0)
        # inflight 0: occupancy 0.0 <= occupancy_low -> calm
    ctrl = FleetController(r, min_replicas=2, max_replicas=4)
    out = ctrl.tick()
    assert out["reason"] == "at-min-replicas"
    assert _scale_decisions(r, action="deferred",
                            reason="at-min-replicas")
    # Below min gate it would act — but cooldown blocks right after
    # an action.
    ctrl2 = FleetController(r, min_replicas=1, max_replicas=4,
                            cooldown_s=60.0)
    with ctrl2._lock:
        ctrl2._last_action_t = time.monotonic()
    assert ctrl2.tick()["reason"] == "cooldown"


def test_scale_down_sentinel_gate_defers_with_verdicts():
    """The PR-15 gate: every candidate victim's verdict is non-healthy
    -> the scale-down is DEFERRED (never destroy what the sentinel
    can't explain), with the verdicts in the decision record."""
    r = _StubRouter(n=2)
    r.sentinel.verdicts = {0: "suspect", 1: "critical"}
    ctrl = FleetController(r, min_replicas=1, max_replicas=4)
    out = ctrl.scale_down()
    assert out["ok"] is False
    assert out["reason"] == "sentinel-cannot-explain"
    evs = _scale_decisions(r, action="deferred",
                           reason="sentinel-cannot-explain")
    assert evs and evs[-1]["sentinel"][0] == "suspect"
    assert ctrl.metrics_snapshot()["scale_events"]["deferred"] == 1
    # An explicitly named victim is gated exactly the same way.
    out = ctrl.scale_down(victim=1)
    assert out["reason"] == "sentinel-cannot-explain"


def test_state_json_and_metrics_snapshot_shape():
    r = _StubRouter()
    ctrl = FleetController(r, min_replicas=1, max_replicas=4,
                           dwell_s=1.5, cooldown_s=9.0)
    st = ctrl.state_json()
    assert st["min_replicas"] == 1 and st["max_replicas"] == 4
    assert st["dwell_s"] == 1.5 and st["cooldown_s"] == 9.0
    assert st["rollout_rung"] == -1 and st["busy"] is False
    assert st["last_signals"] is None
    ctrl.tick()
    assert ctrl.state_json()["last_signals"]["action"] == "hold"
    ms = ctrl.metrics_snapshot()
    assert set(ms) == {"scale_events", "sessions_migrated",
                       "rollout_rung"}
    assert set(ms["scale_events"]) == {"up", "down", "deferred",
                                       "aborted"}


# ---------------------------------------------------------------------------
# Live-fleet acceptance drills
# ---------------------------------------------------------------------------

def _factory(model, tok, made):
    """A replica_factory that builds real started servers with the
    shared tiny geometry and remembers them for teardown."""
    def make(i):
        cb = _mk_batcher(model, tok)
        srv = LLMServer(cb, tokenizer=tok, replica_id=i).start()
        made.append(srv)
        return srv
    return make


def test_scale_down_drain_migrates_sessions_token_identical(model):
    """ACCEPTANCE PIN: scale-down drains the victim by migrating every
    live session's chain to the survivor — zero dropped sessions, and
    every revisit streams token-identically to the 1-replica oracle
    from the NEW home.  The decision log + /metrics + /debug/fleet
    fully explain the action."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    _serve_direct(oracle, tok, [SESSION])
    _serve_direct(oracle, tok, [OTHER])
    want_rev = _serve_direct(oracle, tok, [REVISIT])[0]
    want_oth = _serve_direct(oracle, tok, [OTHER_REVISIT])[0]

    router, servers = _mk_fleet(model, tok, n=2)
    ctrl = FleetController(router, min_replicas=1, max_replicas=2,
                           drain_timeout_s=15.0)
    try:
        # Two live sessions, one per replica (least-loaded balance).
        st, _, h1 = _post(router.address,
                          {"text": SESSION, "max_new_tokens": 6})
        st, _, h2 = _post(router.address,
                          {"text": OTHER, "max_new_tokens": 6})
        homes = {int(h1["X-Replica-Id"]), int(h2["X-Replica-Id"])}
        assert homes == {0, 1}
        router.check_health_now()
        out = ctrl.scale_down(victim=0)
        assert out["ok"] is True and out["replica"] == 0
        drain = out["drain"]
        assert drain["migrated"] >= 1 and drain["ok"] is True
        # Victim permanently out; fleet size gauge reflects it.
        snaps = router.health()["replicas"]
        assert snaps[0]["retired"] is True
        assert snaps[1]["retired"] is False
        # Both sessions keep serving, token-identical, from the
        # survivor — including the one whose chain just migrated.
        st, body, hdrs = _post(router.address,
                               {"text": REVISIT, "max_new_tokens": 6})
        assert st == 200 and body["tokens"] == want_rev
        assert int(hdrs["X-Replica-Id"]) == 1
        st, body, hdrs = _post(
            router.address,
            {"text": OTHER_REVISIT, "max_new_tokens": 6},
        )
        assert st == 200 and body["tokens"] == want_oth
        assert int(hdrs["X-Replica-Id"]) == 1
        # The survivor really holds the migrated chain (warm revisit,
        # not a cold re-prefill).
        dst_chains = servers[1].call_on_loop(
            lambda b: b.resident_chain_keys()
        )
        assert any(len(c) >= 2 for c in dst_chains)
        # Observability: decision records, controller state, metrics.
        assert _scale_decisions(router, action="down", replica=0)
        drains = router.decisions.json(n=16, kind="drain")["decisions"]
        assert drains and drains[-1]["migrated"] == drain["migrated"]
        fleet = router.fleet_health_json()
        assert fleet["controller"]["drains_total"] == 1
        assert fleet["controller"]["sessions_migrated_total"] >= 1
        m = router.metrics_text()
        assert 'llm_fleet_scale_events_total{action="down"} 1' in m
        assert "llm_sessions_migrated_total" in m
        assert "llm_rollout_rung -1" in m
        assert "llm_router_replicas 1" in m
    finally:
        ctrl.close(stop_owned=True)
        router.stop()
        for s in servers:
            s.stop()


def test_tick_driven_scale_up_adds_routable_replica(model):
    """Sustained attainment pressure through tick() grows the fleet:
    the new replica is built by the factory, health-scraped, and
    starts taking traffic; the decision record carries the signals."""
    tok = ByteTokenizer()
    router, servers = _mk_fleet(model, tok, n=2,
                                policy="least-loaded")
    made = []
    ctrl = FleetController(
        router, replica_factory=_factory(model, tok, made),
        min_replicas=1, max_replicas=3,
    )
    try:
        router.check_health_now()
        with router._lock:
            for rep in router._replicas:
                rep.last_health.setdefault("overload", {})[
                    "interactive_attainment"] = 0.1
        out = ctrl.tick()
        assert out["ok"] is True and out["action"] == "up"
        assert out["replica"] == 2 and len(made) == 1
        snaps = router.health()["replicas"]
        assert len(snaps) == 3 and snaps[2]["healthy"] is True
        st, body, _ = _post(router.address,
                            {"text": SESSION, "max_new_tokens": 4})
        assert st == 200 and body["tokens"]
        evs = _scale_decisions(router, action="up", replica=2)
        assert evs and evs[-1]["signals"]["attainment_min"] == 0.1
        m = router.metrics_text()
        assert 'llm_fleet_scale_events_total{action="up"} 1' in m
        assert "llm_router_replicas 3" in m
    finally:
        ctrl.close(stop_owned=True)
        router.stop()
        for s in servers:
            s.stop()


def test_rollout_same_weights_all_rungs_pass(model):
    """Zero-downtime rollout happy path: every rung drains, swaps to
    the new instance, and passes the canary gate (same weights ->
    rung 0 pins the rollout oracle, rung 1 matches it); the final
    fleet-wide sweep is unanimously clean and the fleet keeps
    serving token-identically."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    want = _serve_direct(oracle, tok, [SESSION])[0]

    router, servers = _mk_fleet(model, tok, n=2)
    made = []
    ctrl = FleetController(router, drain_timeout_s=15.0)
    try:
        out = ctrl.rollout(_factory(model, tok, made))
        assert out["ok"] is True, out
        assert out["planned"] == 2
        assert [r["ok"] for r in out["rungs"]] == [True, True]
        assert len(made) == 2
        # Every slot now runs a NEW instance; none retired.
        snaps = router.health()["replicas"]
        assert len(snaps) == 2
        assert all(not s["retired"] for s in snaps)
        st, body, _ = _post(router.address,
                            {"text": SESSION, "max_new_tokens": 6})
        assert st == 200 and body["tokens"] == want
        rungs = router.decisions.json(n=16, kind="rollout_rung")["decisions"]
        assert [ev["ok"] for ev in rungs] == [True, True]
        assert rungs[0]["gate"] == "oracle-pinned"
        assert rungs[1]["gate"] == "oracle-match"
        top = router.decisions.json(n=4, kind="rollout")["decisions"]
        assert top and top[-1]["ok"] is True
        assert ctrl.state_json()["rollouts_total"] == 1
        assert ctrl.state_json()["rollbacks_total"] == 0
        assert "llm_rollout_rung -1" in router.metrics_text()
    finally:
        ctrl.close(stop_owned=True)
        router.stop()
        for s in servers:
            s.stop()


def test_rollout_bad_rung_fails_canary_gate_and_rolls_back(model):
    """A rung whose new instance emits WRONG tokens fails the canary
    gate (rollout-oracle mismatch — caught even though the fleet
    majority still runs old weights) and auto-rolls back through
    rollback_factory: the fleet ends full-size, serving, with the
    rollback recorded."""
    params, config = model
    bad_params = init_params(jax.random.PRNGKey(9), config)
    tok = ByteTokenizer()
    router, servers = _mk_fleet(model, tok, n=2)
    made = []

    def factory(i):
        p = params if i == 0 else bad_params
        cb = ContinuousBatcher(
            p, config, n_slots=2, max_len=64,
            stop_tokens=tuple(tok.stop_tokens),
        )
        srv = LLMServer(cb, tokenizer=tok, replica_id=i).start()
        made.append(srv)
        return srv

    ctrl = FleetController(router, drain_timeout_s=15.0)
    try:
        out = ctrl.rollout(factory, rollback_factory=_factory(
            model, tok, made))
        assert out["ok"] is False
        assert "canary-gate" in out["reason"]
        assert out["rungs"][0]["ok"] is True
        assert out["rungs"][1]["ok"] is False
        assert out["rungs"][1]["rollback"] == "rolled-back"
        assert ctrl.state_json()["rollbacks_total"] == 1
        # Fleet is whole and serving (rung 0 new weights == same
        # params; rung 1 rolled back to same params).
        snaps = router.health()["replicas"]
        assert len(snaps) == 2
        assert all(not s["retired"] for s in snaps)
        st, body, _ = _post(router.address,
                            {"text": SESSION, "max_new_tokens": 4})
        assert st == 200 and body["tokens"]
        rungs = router.decisions.json(n=16, kind="rollout_rung")["decisions"]
        assert rungs[-1]["ok"] is False
        assert "oracle-mismatch" in rungs[-1]["reason"]
        assert "llm_rollout_rung -1" in router.metrics_text()
    finally:
        ctrl.close(stop_owned=True)
        router.stop()
        for s in servers:
            s.stop()


def test_migrate_chain_no_capacity_leaves_source_intact(model):
    """REGRESSION PIN: the residency-gated demote.  A destination
    with zero pool capacity lands nothing on import — the scheduler
    records the benign empty outcome and the SOURCE keeps its full
    HBM chain (an export must never cost the fleet its only copy),
    so the session keeps serving warm from the source."""
    tok = ByteTokenizer()
    oracle = _mk_batcher(model, tok)
    _serve_direct(oracle, tok, [SESSION])
    want = _serve_direct(oracle, tok, [REVISIT])[0]

    router, servers = _mk_fleet(model, tok, n=2)
    try:
        st, _, hdrs = _post(router.address,
                            {"text": SESSION, "max_new_tokens": 6})
        src = int(hdrs["X-Replica-Id"])
        dst = 1 - src
        router.check_health_now()
        chains = servers[src].call_on_loop(
            lambda b: b.resident_chain_keys()
        )
        chain = max(chains, key=len)
        assert len(chain) >= 2
        # Choke the destination pool to zero capacity for the import.
        servers[dst].call_on_loop(
            lambda b: setattr(b, "_capacity", lambda: 0)
        )
        empties_before = router.handoffs_empty_total
        router.migrate_chain([k.hex() for k in chain], src, dst)
        assert router.wait_handoffs(timeout_s=10.0)
        assert router.handoffs_empty_total == empties_before + 1
        evs = router.decisions.json(n=16, kind="handoff_empty")["decisions"]
        assert evs and evs[-1]["reason"] == (
            "already-resident-or-no-capacity"
        )
        # Source HBM chain fully intact: residency-gated demote never
        # fired (destination holds nothing).
        depth = servers[src].call_on_loop(
            lambda b: len(b._match_prefix(list(chain)).blocks)
        )
        assert depth == len(chain)
        assert not servers[dst].call_on_loop(
            lambda b: b.resident_chain_keys()
        )
        servers[dst].call_on_loop(
            lambda b: delattr(b, "_capacity")
        )
        # The session still serves warm + token-identical from the
        # source.
        st, body, hdrs = _post(router.address,
                               {"text": REVISIT, "max_new_tokens": 6})
        assert st == 200 and body["tokens"] == want
        assert int(hdrs["X-Replica-Id"]) == src
    finally:
        router.stop()
        for s in servers:
            s.stop()


def test_drain_replica_operator_entry_no_survivor(model):
    """drain_replica on a 1-replica fleet fails with "no-survivor"
    when there are live chains to move — and the replica RESUMES
    admission (nothing stranded half-drained)."""
    tok = ByteTokenizer()
    router, servers = _mk_fleet(model, tok, n=1)
    ctrl = FleetController(router, drain_timeout_s=10.0)
    try:
        st, _, _ = _post(router.address,
                         {"text": SESSION, "max_new_tokens": 6})
        assert st == 200
        out = ctrl.drain_replica(0)
        assert out["ok"] is False and out["reason"] == "no-survivor"
        snap = router.health()["replicas"][0]
        assert snap["retiring"] is False and snap["retired"] is False
        st, body, _ = _post(router.address,
                            {"text": REVISIT, "max_new_tokens": 4})
        assert st == 200 and body["tokens"]
        assert ctrl.state_json()["drains_failed_total"] == 1
    finally:
        ctrl.close()
        router.stop()
        for s in servers:
            s.stop()
