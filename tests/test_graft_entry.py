"""Driver-contract smoke tests for __graft_entry__ (CPU, 8 virtual devs)."""

import sys
from pathlib import Path

import jax
import pytest
from conftest import skip_if_xla_partition_id_skew

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft


def test_entry_returns_jittable_fn():
    fn, args = graft.entry()
    # Validate traceability/shapes without paying a full CPU execution.
    out = jax.eval_shape(fn, *args)
    params, tokens, positions = args
    assert out.shape == (*tokens.shape, 32000)


# slow (r06 budget rebalance): the 8-device dryrun sweep is ~70 s of
# CPU compiles — the single largest tier-1 item — and its mesh
# configurations are also exercised by test_partition / test_pipeline
# and the MULTICHIP_r* trajectory; `pytest -m slow` / the full suite
# keep it covered.
@pytest.mark.slow
def test_dryrun_multichip_8():
    try:
        graft.dryrun_multichip(8)
    except Exception as e:  # noqa: BLE001 — skew-detect, re-raise the rest
        skip_if_xla_partition_id_skew(e)


def test_mesh_factors():
    assert graft._mesh_factors(8) == (1, 2, 2, 2)
    assert graft._mesh_factors(16) == (2, 2, 2, 2)
    assert graft._mesh_factors(4) == (1, 1, 2, 2)
    assert graft._mesh_factors(2) == (1, 1, 1, 2)
    assert graft._mesh_factors(1) == (1, 1, 1, 1)
    for n in (1, 2, 4, 6, 8, 16):
        d, f, s, t = graft._mesh_factors(n)
        assert d * f * s * t == n
