"""Unit parity tests for core ops vs the independent torch oracle.

Mirrors tier 1 of the reference test strategy (SURVEY.md §4 /
``/root/reference/jax_test.py:528-592``): same random inputs into both
implementations, tight fp32 tolerances.
"""

import numpy as np
import jax.numpy as jnp
import torch

from jax_llama_tpu.ops import (
    apply_rope,
    attention_bias,
    greedy,
    repeat_kv,
    rms_norm,
    rope_table,
    sdpa,
    top_k_filter,
    top_p_filter,
)
import torch_oracle as oracle

# Match the reference harness's trial count (jax_test.py:528-592 runs its
# module parity checks 128 times per op).
TRIALS = 128


def test_rms_norm_matches_oracle():
    for _ in range(TRIALS):
        x = np.random.randn(2, 5, 32).astype(np.float32)
        scale = np.random.randn(32).astype(np.float32)
        got = rms_norm(jnp.asarray(x), jnp.asarray(scale), 1e-5)
        want = oracle.rms_norm(torch.from_numpy(x), torch.from_numpy(scale), 1e-5)
        np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-5, rtol=1e-5)


def test_rope_matches_complex_oracle():
    """The runtime rotation is half-split over PERMUTED features
    (models.llama.rope_permute); permute -> rotate -> unpermute must equal
    Meta's interleaved complex rotation of the raw vector exactly."""
    from jax_llama_tpu.models.llama import rope_permute

    hd, max_pos, theta = 16, 64, 10000.0
    cos, sin = rope_table(hd, max_pos, theta)
    freqs = oracle.rope_freqs_cis(hd, max_pos, theta)
    for _ in range(TRIALS):
        x = np.random.randn(2, 7, 4, hd).astype(np.float32)
        pos = np.random.randint(0, max_pos, size=(2, 7))
        got = rope_permute(
            np.asarray(
                apply_rope(
                    jnp.asarray(rope_permute(x)), cos, sin, jnp.asarray(pos)
                )
            ),
            inverse=True,
        )
        want = oracle.apply_rope(
            torch.from_numpy(x), freqs, torch.from_numpy(pos)
        )
        np.testing.assert_allclose(got, want.numpy(), atol=1e-5, rtol=1e-5)


def test_rope_large_theta_llama3():
    from jax_llama_tpu.models.llama import rope_permute

    hd = 128
    cos, sin = rope_table(hd, 256, 500000.0)
    freqs = oracle.rope_freqs_cis(hd, 256, 500000.0)
    x = np.random.randn(1, 9, 2, hd).astype(np.float32)
    pos = np.arange(9)[None, :]
    got = rope_permute(
        np.asarray(
            apply_rope(jnp.asarray(rope_permute(x)), cos, sin, jnp.asarray(pos))
        ),
        inverse=True,
    )
    want = oracle.apply_rope(torch.from_numpy(x), freqs, torch.from_numpy(pos))
    np.testing.assert_allclose(got, want.numpy(), atol=1e-5, rtol=1e-5)


def test_llama31_scaled_rope():
    from jax_llama_tpu.ops.rope import llama3_scale_inv_freq

    hd, theta = 128, 500000.0
    inv = 1.0 / (theta ** (np.arange(0, hd, 2) / hd))
    scaled = llama3_scale_inv_freq(inv)
    wavelen = 2 * np.pi / inv
    # High-frequency (short wavelength) components unchanged.
    hi = wavelen < 8192 / 4
    np.testing.assert_array_equal(scaled[hi], inv[hi])
    # Low-frequency components divided by the 8x scale factor.
    lo = wavelen > 8192 / 1
    np.testing.assert_allclose(scaled[lo], inv[lo] / 8.0)
    # Band in between interpolates monotonically between the two regimes.
    mid = ~(hi | lo)
    assert ((scaled[mid] >= inv[mid] / 8.0) & (scaled[mid] <= inv[mid])).all()
    # And the table plumbing: scaled table differs from unscaled.
    c0, _ = rope_table(hd, 32, theta)
    c1, _ = rope_table(hd, 32, theta, use_scaled_rope=True)
    assert not np.allclose(c0, c1)


def test_repeat_kv():
    x = np.random.randn(2, 3, 2, 4).astype(np.float32)
    got = np.asarray(repeat_kv(jnp.asarray(x), 3))
    want = torch.from_numpy(x).repeat_interleave(3, dim=2).numpy()
    np.testing.assert_allclose(got, want)


def test_sdpa_matches_manual_softmax_attention():
    B, T, H, KVH, D = 2, 6, 4, 2, 8
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, T, KVH, D).astype(np.float32)
    v = np.random.randn(B, T, KVH, D).astype(np.float32)
    pos = np.tile(np.arange(T), (B, 1))
    bias = attention_bias(jnp.asarray(pos), jnp.asarray(pos))
    got = np.asarray(sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias))

    qt, kt, vt = map(torch.from_numpy, (q, k, v))
    kt = kt.repeat_interleave(H // KVH, dim=2)
    vt = vt.repeat_interleave(H // KVH, dim=2)
    scores = torch.einsum("bthd,bshd->bhts", qt, kt) / np.sqrt(D)
    causal = torch.tril(torch.ones(T, T, dtype=torch.bool))
    scores = scores.masked_fill(~causal, float("-inf"))
    want = torch.einsum("bhts,bshd->bthd", torch.softmax(scores, -1), vt).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_attention_bias_padding_slots_never_attended():
    pos = jnp.asarray([[-1, -1, 0, 1]])
    qpos = jnp.maximum(pos, 0)
    slot_pos = jnp.where(pos >= 0, qpos, -1)
    bias = attention_bias(qpos, slot_pos, slot_pos >= 0)
    b = np.asarray(bias)[0, 0]  # [T, S]
    assert (b[:, 0] < -1e30).all() and (b[:, 1] < -1e30).all()
    # Every query row must still have at least one attendable slot (no NaN).
    assert (b.max(axis=-1) == 0).all()


def test_greedy():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.0]])
    np.testing.assert_array_equal(np.asarray(greedy(logits)), [1, 0])


def test_top_p_filter_keeps_nucleus():
    # probs ~ [0.6, 0.3, 0.07, 0.03]; top_p=0.8 keeps the first two.
    p = np.array([0.6, 0.3, 0.07, 0.03])
    logits = jnp.asarray(np.log(p))[None, :]
    out = np.asarray(top_p_filter(logits, 0.8))[0]
    assert out[0] > -1e30 and out[1] > -1e30
    assert out[2] < -1e30 and out[3] < -1e30


def test_top_p_filter_always_keeps_best():
    logits = jnp.asarray([[10.0, 0.0, -5.0]])
    out = np.asarray(top_p_filter(logits, 0.01))[0]
    assert out[0] > -1e30
    assert out[1] < -1e30 and out[2] < -1e30


def test_top_p_zero_keeps_best_token():
    # Degenerate top_p=0.0 must still behave as greedy, not uniform-random.
    logits = jnp.asarray([[1.0, 4.0, 2.0]])
    out = np.asarray(top_p_filter(logits, 0.0))[0]
    assert out[1] > -1e30
    assert out[0] < -1e30 and out[2] < -1e30


def test_top_k_filter():
    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = np.asarray(top_k_filter(logits, 2))[0]
    assert out[1] > -1e30 and out[2] > -1e30
    assert out[0] < -1e30 and out[3] < -1e30
