"""Graceful degradation: kernel quarantine with XLA fallback, the
non-finite guard, and drain-on-signal shutdown.

The invariants pinned here:
  * a feature that keeps failing (Pallas paged/flash kernel, speculative
    decode, prefix cache) is QUARANTINED onto its always-correct
    fallback after N attributable failures — the server stays up, every
    request completes, and greedy outputs are token-identical to the
    healthy path;
  * /healthz reports the full degraded state and the feature recovers
    via a probe rebuild after the cooldown;
  * quarantine does NOT consume the crash-recovery budget (degrading
    removes the crash cause; the breaker is for unexplained failures);
  * non-finite logits fail only the offending request with a clean HTTP
    500 — other requests and the server itself are untouched;
  * drain mode finishes in-flight requests, 503s new ones with
    Retry-After, and exits the loop — bounded by the drain timeout.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.degrade import FEATURES, DegradeManager
from jax_llama_tpu.faults import FaultInjector, InjectedFault
from jax_llama_tpu.server import LLMServer
from jax_llama_tpu.serving import ContinuousBatcher

pytestmark = pytest.mark.faults

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)

PROMPTS = [[5, 17, 99, 3], [7, 8, 9], [11, 12, 13], [2, 3, 4]]
MAX_NEW = 8


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


@pytest.fixture(scope="module")
def reference(model):
    """Fault-free greedy outputs for PROMPTS (the identity oracle)."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(list(p), max_new_tokens=MAX_NEW) for p in PROMPTS]
    out = cb.run_to_completion()
    return [out[r] for r in rids]


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read().decode()


def _health(url):
    try:
        _, body = _get(url, "/healthz")
    except urllib.error.HTTPError as e:
        body = e.read().decode()
    return json.loads(body)


# ---------------------------------------------------------------------------
# DegradeManager state machine (no jax involved)
# ---------------------------------------------------------------------------

def test_state_machine_threshold_window_probe():
    clock = [0.0]
    m = DegradeManager(
        threshold=3, window_s=10.0, cooldown_s=5.0, clock=lambda: clock[0]
    )
    f = "paged_kernel"
    assert m.enabled(f) and not m.degraded()
    assert m.record_failure(f) is False
    assert m.record_failure(f) is False
    assert m.enabled(f)                      # below threshold
    assert m.record_failure(f) is True       # 3rd inside window: quarantine
    assert not m.enabled(f) and m.degraded()
    assert m.quarantined() == (f,)
    assert m.due_probes() == []
    clock[0] = 5.0                           # cooldown elapsed
    assert m.due_probes() == [f]
    m.start_probe(f)
    assert m.enabled(f)                      # probing counts as enabled
    assert m.snapshot()[f]["state"] == "probing"
    # Probe failure: straight back to quarantine, cooldown restarts.
    assert m.record_failure(f) is True
    assert not m.enabled(f)
    clock[0] = 9.9
    assert m.due_probes() == []
    clock[0] = 10.0
    m.start_probe(f)
    assert m.record_success(f) is True       # probe passed
    assert m.enabled(f) and not m.degraded()
    assert m.snapshot()[f]["state"] == "healthy"
    st = m.snapshot()[f]
    assert st["failures_total"] == 4 and st["quarantines_total"] == 2
    assert st["probes_total"] == 2


def test_state_machine_window_expires_failures():
    clock = [0.0]
    m = DegradeManager(
        threshold=2, window_s=1.0, cooldown_s=1.0, clock=lambda: clock[0]
    )
    assert m.record_failure("spec_decode") is False
    clock[0] = 2.0                           # first failure aged out
    assert m.record_failure("spec_decode") is False
    clock[0] = 2.5
    assert m.record_failure("spec_decode") is True


def test_state_machine_rejects_unknown_feature():
    m = DegradeManager()
    with pytest.raises(KeyError):
        m.record_failure("nosuch")
    # success outside probing is a no-op, never a transition
    assert m.record_success(FEATURES[0]) is False


def test_manager_stats_and_snapshot_shapes():
    m = DegradeManager()
    snap, stats = m.snapshot(), m.stats()
    for f in FEATURES:
        assert snap[f]["state"] == "healthy"
        assert stats[f"feature_quarantined_{f}"] == 0


# ---------------------------------------------------------------------------
# Kernel quarantine: repeated kernel faults -> XLA fallback, server stays up
# ---------------------------------------------------------------------------

def test_paged_kernel_quarantine_keeps_serving_identically(
    model, reference
):
    """Every decode step on the kernel path faults: after the threshold
    the feature is quarantined, the batcher rebuilds onto the
    gathered-view XLA fallback, and every request completes with greedy
    outputs token-identical to the healthy path.  The crash-recovery
    breaker does NOT trip (quarantining forgives the budget)."""
    params, config = model
    inj = FaultInjector("paged_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    results = {}
    with LLMServer(
        cb, max_recoveries=3, quarantine_threshold=3,
        quarantine_cooldown_s=3600.0,  # no probe during this test
    ) as srv:
        def call(i):
            try:
                _, body = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )
                results[i] = body["tokens"]
            except Exception as e:  # noqa: BLE001
                results[i] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        for i in range(len(PROMPTS)):
            assert results[i] == reference[i], i

        h = _health(srv.address)
        assert h["ok"] is True                    # degraded, NOT down
        assert h["degraded"] is True
        assert h["quarantined"] == ["paged_kernel"]
        feat = h["features"]["paged_kernel"]
        assert feat["state"] == "quarantined"
        assert feat["quarantines_total"] == 1
        assert feat["probe_in_s"] > 0
        assert srv.quarantine_rebuilds_total == 1
        assert inj.injected["paged_kernel"] == 3  # threshold, then silent
        _, mtext = _get(srv.address, "/metrics")
        assert "llm_feature_quarantined_paged_kernel 1" in mtext
        assert "llm_quarantine_rebuilds_total 1" in mtext


def test_quarantined_kernel_recovers_after_cooldown(model, reference):
    """Indexed faults kill the first three kernel steps; after the
    cooldown the loop probes (rebuild with the kernel re-enabled), the
    probe step succeeds, and /healthz reports the feature healthy —
    with requests before, during, and after all token-identical."""
    params, config = model
    inj = FaultInjector(
        "paged_kernel@0:error,paged_kernel@1:error,paged_kernel@2:error"
    )
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    with LLMServer(
        cb, quarantine_threshold=3, quarantine_cooldown_s=0.5
    ) as srv:
        _, body = _post(
            srv.address, {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == reference[0]
        assert _health(srv.address)["quarantined"] == ["paged_kernel"]
        time.sleep(0.7)  # past the cooldown; the probe needs a step
        _, body = _post(
            srv.address, {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == reference[1]
        h = _health(srv.address)
        assert h["features"]["paged_kernel"]["state"] == "healthy"
        assert h["degraded"] is False and h["ok"] is True
        assert srv.probe_rebuilds_total == 1
        _, mtext = _get(srv.address, "/metrics")
        assert "llm_feature_quarantined_paged_kernel 0" in mtext
        assert "llm_probe_rebuilds_total 1" in mtext


def test_spec_decode_quarantine_falls_back_to_plain(model, reference):
    """A speculative batcher whose every round faults quarantines
    spec_decode and rebuilds WITHOUT the draft model — greedy outputs
    are token-identical (the draft only ever changes speed)."""
    params, config = model
    inj = FaultInjector("spec_decode~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64,
        draft_params=params, draft_config=config, n_draft=2,
        fault_injector=inj,
    )
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=3600.0
    ) as srv:
        _, body = _post(
            srv.address, {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == reference[0]
        h = _health(srv.address)
        assert h["quarantined"] == ["spec_decode"]
        assert not srv.batcher.spec  # the fallback batcher is plain
        # A follow-up request runs entirely on the plain path.
        _, body = _post(
            srv.address, {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == reference[1]


def test_flash_attention_quarantine_rebuilds_onto_xla(model):
    """attn_impl='auto' prefills through the Pallas flash kernel; when
    every flash dispatch faults the feature quarantines and the batcher
    rebuilds with attn_impl='xla' — outputs identical to a pure-xla
    batcher (after quarantine every completed token IS the xla path)."""
    params, config = model
    auto_cfg = config.replace(attn_impl="auto")
    cold = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    rid = cold.submit(list(PROMPTS[0]), max_new_tokens=MAX_NEW)
    want = cold.run_to_completion()[rid]

    inj = FaultInjector("flash_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, auto_cfg, n_slots=1, max_len=64, fault_injector=inj
    )
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=3600.0
    ) as srv:
        _, body = _post(
            srv.address, {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW}
        )
        assert body["tokens"] == want
        h = _health(srv.address)
        assert h["quarantined"] == ["flash_attention"]
        assert srv.batcher.config.attn_impl == "xla"


# slow (r06 budget rebalance, ~12 s): still in `make faults` / `make
# chaos`; the cheap flash-quarantine cells above keep tier-1 coverage.
@pytest.mark.slow
def test_flash_quarantine_during_fused_prefill_keeps_admission(model):
    """flash_kernel faults during FUSED prefill chunks (attn auto, a
    >8-token chunk riding the decode dispatch) quarantine
    flash_attention: the batcher rebuilds onto attn_impl='xla', the
    mid-prefill admission replays instead of dropping, and fused
    scheduling keeps running on the gathered path afterwards."""
    params, config = model
    auto_cfg = config.replace(attn_impl="auto")
    long_prompt = np.random.RandomState(3).randint(1, 128, 40).tolist()
    cb0 = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=8,
    )
    ra = cb0.submit(list(PROMPTS[0]), max_new_tokens=24)
    rb = cb0.submit(list(long_prompt), max_new_tokens=MAX_NEW)
    rc = cb0.submit(list(PROMPTS[1]), max_new_tokens=MAX_NEW)
    out0 = cb0.run_to_completion()
    want_a, want_b, want_c = out0[ra], out0[rb], out0[rc]

    # block_size=8 keeps the resident's COLD 8-token classic prefill on
    # the xla path (flash needs a >8-token chunk), so the ONLY flash
    # dispatches are the fused prefill chunks (budget 16 > 8).
    inj = FaultInjector("flash_kernel~1.0:error")
    cb = ContinuousBatcher(
        params, auto_cfg, n_slots=2, max_len=64, block_size=8,
        decode_chunk=4, prefill_budget=16, fault_injector=inj,
    )
    with LLMServer(
        cb, quarantine_threshold=1, quarantine_cooldown_s=3600.0
    ) as srv:
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps({
                "prompt": PROMPTS[0], "max_new_tokens": 24,
                "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            first = json.loads(resp.readline())
            assert "token" in first
            # Admits mid-decode -> fused prefill on flash -> fault ->
            # flash_attention quarantined, admission replayed.
            _, body = _post(
                srv.address,
                {"prompt": long_prompt, "max_new_tokens": MAX_NEW},
            )
            assert body["tokens"] == want_b  # admission NOT dropped
            assert srv.degrade.quarantined() == ("flash_attention",)
            assert srv.batcher.config.attn_impl == "xla"
            # Fused scheduling survived the rebuild; a follow-up warm
            # admission rides it on the gathered/xla path.
            assert srv.batcher.prefill_budget == 16
            _, body2 = _post(
                srv.address,
                {"prompt": PROMPTS[1], "max_new_tokens": MAX_NEW},
            )
            assert body2["tokens"] == want_c
            lines = [first] + [
                json.loads(ln) for ln in resp.read().splitlines()
            ]
        streamed = [ln["token"] for ln in lines[:-1]]
        assert streamed == want_a  # resident: no dup, no gap
        assert inj.injected_total >= 1


def test_prefix_cache_quarantine_serves_cold(model):
    """Every prefix-cache-hit suffix dispatch faults: the feature
    quarantines and later sharers admit through cold full prefill —
    token-identical (a hit changes what is computed, never what is
    emitted)."""
    params, config = model
    rng = np.random.RandomState(3)
    base = rng.randint(1, 128, size=40).tolist()  # 2 full keyed blocks
    variants = [base + [3], base + [9, 4], base + [6]]

    cb0 = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                            block_size=16, prefix_cache=False)
    want = []
    for p in variants:
        r = cb0.submit(list(p), max_new_tokens=6)
        want.append(cb0.run_to_completion()[r])

    inj = FaultInjector("suffix_insert~1.0:error")
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=128,
                           block_size=16, fault_injector=inj)
    with LLMServer(
        cb, quarantine_threshold=2, quarantine_cooldown_s=3600.0
    ) as srv:
        got = []
        for p in variants:
            _, body = _post(
                srv.address, {"prompt": p, "max_new_tokens": 6}
            )
            got.append(body["tokens"])
        assert got == want
        h = _health(srv.address)
        assert h["quarantined"] == ["prefix_cache"]
        assert not srv.batcher.prefix_cache_enabled


def test_unattributable_faults_still_trip_the_breaker(model):
    """Generic step faults (no feature attribution) must keep PR 1's
    hard-drain contract: past max_recoveries the loop gives up and
    clients get 503 — quarantine never swallows an unexplained crash
    loop."""
    params, config = model
    inj = FaultInjector("step~1.0:error")
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=64, fault_injector=inj
    )
    with LLMServer(cb, max_recoveries=1, recovery_window_s=60.0) as srv:
        try:
            _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 2})
            assert False, "expected HTTP 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        h = _health(srv.address)
        assert h["loop_alive"] is False
        assert h["degraded"] is False  # nothing was quarantined


# ---------------------------------------------------------------------------
# Non-finite guard
# ---------------------------------------------------------------------------

def test_nonfinite_logits_fail_only_that_request(model, reference):
    """An armed ``nan`` fault poisons one row mid-decode: that request
    gets a clean 500, every other request completes identically, and
    the server stays healthy."""
    params, config = model
    inj = FaultInjector("step@2:nan")
    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, fault_injector=inj
    )
    results = {}
    with LLMServer(cb) as srv:
        def call(i):
            try:
                _, body = _post(
                    srv.address,
                    {"prompt": PROMPTS[i], "max_new_tokens": MAX_NEW},
                )
                results[i] = body["tokens"]
            except urllib.error.HTTPError as e:
                results[i] = (e.code, json.loads(e.read())["error"])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads)
        codes = [r for r in results.values() if isinstance(r, tuple)]
        toks = [r for r in results.values() if isinstance(r, list)]
        assert len(codes) == 1 and len(toks) == 1, results
        code, msg = codes[0]
        assert code == 500 and "non-finite" in msg
        assert toks[0] in reference  # the survivor is exact
        assert srv.nonfinite_failed_total == 1
        h = _health(srv.address)
        assert h["ok"] is True  # one bad request never degrades health
        _, mtext = _get(srv.address, "/metrics")
        assert "llm_nonfinite_requests_failed_total 1" in mtext
        assert "llm_nonfinite_rows_total 1" in mtext


def test_real_nan_params_fail_requests_cleanly(model):
    """Genuinely non-finite weights (NaN lm head — the real failure the
    guard exists for): every request fails with a clean 500, nothing
    streams garbage, and the serving loop survives."""
    params, config = model
    bad = dict(params)
    bad["lm_head"] = params["lm_head"] * float("nan")
    cb = ContinuousBatcher(bad, config, n_slots=2, max_len=64)
    with LLMServer(cb) as srv:
        for p in PROMPTS[:2]:
            try:
                _post(srv.address, {"prompt": p, "max_new_tokens": 4})
                assert False, "expected HTTP 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "non-finite" in json.loads(e.read())["error"]
        assert _health(srv.address)["ok"] is True
        assert srv.nonfinite_failed_total == 2
        assert not srv.batcher.pending()  # slots and blocks all freed


def test_nonfinite_spec_round_fails_request(model):
    """The speculative verify path's guard: NaN target logits abort the
    request without committing the poisoned round."""
    params, config = model
    bad = dict(params)
    bad["lm_head"] = params["lm_head"] * float("nan")
    cb = ContinuousBatcher(
        bad, config, n_slots=1, max_len=64,
        draft_params=params, draft_config=config, n_draft=2,
    )
    rid = cb.submit(list(PROMPTS[0]), max_new_tokens=4)
    out = cb.run_to_completion()
    failed = cb.pop_failed()
    assert rid not in out
    assert failed and failed[0][0] == rid
    assert not cb.pending()


def test_nonfinite_prompt_blocks_never_enter_prefix_cache(model):
    """A poisoned request's freshly prefilled blocks must be unpublished
    from the prefix index — a later identical prompt on healed weights
    must not hit KV written by the NaN run."""
    params, config = model
    bad = dict(params)
    bad["lm_head"] = params["lm_head"] * float("nan")
    prompt = list(np.random.RandomState(5).randint(1, 128, size=40))
    cb = ContinuousBatcher(bad, config, n_slots=1, max_len=128,
                           block_size=16)
    rid = cb.submit(prompt, max_new_tokens=4)
    cb.run_to_completion()
    assert cb.pop_failed()[0][0] == rid
    assert cb.stats()["radix_nodes_total"] == 0  # nothing published
    assert len(cb.free_blocks) == cb.n_blocks  # everything returned


# ---------------------------------------------------------------------------
# Drain-on-signal
# ---------------------------------------------------------------------------

def test_drain_finishes_inflight_and_503s_new(model, reference):
    """begin_drain with a stream mid-flight: the stream runs to
    completion token-identically, a new POST gets 503 + Retry-After,
    /healthz flips to draining, and the loop exits on its own."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    srv = LLMServer(cb, drain_timeout_s=60.0).start()
    try:
        # Warm the compile caches so the drained request finishes fast.
        _post(srv.address, {"prompt": [4, 5], "max_new_tokens": 2})
        result = {}

        def call():
            result["r"] = _post(
                srv.address,
                {"prompt": PROMPTS[0], "max_new_tokens": MAX_NEW},
            )

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.15)
        srv.begin_drain()
        try:
            _post(srv.address, {"prompt": [1, 2], "max_new_tokens": 2})
            assert False, "expected HTTP 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) >= 1
            assert "drain" in json.loads(e.read())["error"]
        h = _health(srv.address)
        assert h["draining"] is True and h["ok"] is False
        assert h["drain_remaining_s"] is not None
        t.join(timeout=300)
        assert not t.is_alive()
        status, body = result["r"]
        assert status == 200 and body["tokens"] == reference[0]
        assert srv.wait_drained(60)
    finally:
        srv.stop()


def test_drain_timeout_bounds_shutdown(model):
    """A drain deadline in the past: the in-flight request is failed
    with 503 instead of holding shutdown hostage.  An injected step
    delay holds the request mid-generation so the drain deterministically
    catches it in flight."""
    params, config = model
    inj = FaultInjector("step@0:delay=1.5")
    cb = ContinuousBatcher(
        params, config, n_slots=1, max_len=128, fault_injector=inj
    )
    srv = LLMServer(cb).start()
    try:
        result = {}

        def call():
            try:
                result["r"] = _post(
                    srv.address,
                    {"prompt": [7, 8, 9], "max_new_tokens": 100},
                )
            except urllib.error.HTTPError as e:
                result["r"] = (e.code, json.loads(e.read())["error"])

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.3)  # inside compile or the held step
        srv.begin_drain(timeout_s=0.0)
        t.join(timeout=300)
        assert not t.is_alive()
        code, msg = result["r"]
        assert code == 503 and "drain timeout" in msg
        assert srv.wait_drained(60)
    finally:
        srv.stop()


def test_drain_idempotent_and_immediate_when_idle(model):
    """Draining an idle server exits the loop promptly; a second
    begin_drain keeps the first deadline."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    srv = LLMServer(cb, drain_timeout_s=30.0).start()
    try:
        srv.begin_drain(timeout_s=10.0)
        dl = srv._drain_deadline
        srv.begin_drain(timeout_s=99999.0)
        assert srv._drain_deadline == dl
        assert srv.wait_drained(30)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Trace-time kernel hooks + run.py wiring
# ---------------------------------------------------------------------------

def test_kernel_trace_hooks_fire_and_carry_site():
    """One faults.install_trace_hook arms every kernel entry point; the
    raised fault carries the site name (the attribution key)."""
    import jax.numpy as jnp

    from jax_llama_tpu import spec_decode as sd
    from jax_llama_tpu.faults import install_trace_hook
    from jax_llama_tpu.ops import paged_attention as pa
    from jax_llama_tpu.ops.flash_attention import flash_attention

    inj = FaultInjector(
        "flash_kernel@0:error,paged_kernel@0:error,spec_decode@0:error"
    )
    install_trace_hook(inj.fire)
    try:
        q = jnp.zeros((1, 8, 2, 8), jnp.float32)
        kv = jnp.zeros((1, 8, 2, 8), jnp.float32)
        pos = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(InjectedFault) as ei:
            flash_attention(q, kv, kv, pos, pos)
        assert ei.value.site == "flash_kernel"
        with pytest.raises(InjectedFault) as ei:
            pa.paged_pool_attention(
                jnp.zeros((1, 2, 2, 8), jnp.float32),
                jnp.zeros((2, 2, 4, 8, 8), jnp.float32),
                jnp.zeros((2, 2, 4, 8, 8), jnp.float32),
                jnp.zeros((4, 8), jnp.int32),
                jnp.zeros((1, 2), jnp.int32),
                jnp.zeros((1,), jnp.int32),
            )
        assert ei.value.site == "paged_kernel"
        with pytest.raises(InjectedFault) as ei:
            sd.generate_speculative(
                None, None, None, None,
                target_config=None, draft_config=None, gen_config=None,
            )
        assert ei.value.site == "spec_decode"
    finally:
        install_trace_hook(None)
    assert inj.calls["flash_kernel"] == 1
    assert inj.calls["paged_kernel"] == 1
    assert inj.calls["spec_decode"] == 1


@pytest.mark.slow
def test_run_cli_degrade_flags(tmp_path, capsys, monkeypatch):
    """The CLI wires --quarantine-*/--drain-timeout-s into the server
    and a kernel-fault drill degrades (quarantine visible in /healthz)
    instead of draining; the trace hooks are uninstalled afterwards.

    Slow tier (PR-10 budget rebalance: tier-1 measured at its 870 s
    ceiling): quarantine/degradation behavior itself stays pinned
    tier-1 by the rest of this module; this cell is the end-to-end
    CLI flag-threading drill (checkpoint restore + live server), and
    runs in the unfiltered suite and `make chaos`."""
    import sys

    import jax_llama_tpu.run as run_cli
    from jax_llama_tpu import faults as faults_mod
    from jax_llama_tpu.convert.checkpoint import save_checkpoint

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    hits = {}

    def hook(srv):
        assert srv.drain_timeout_s == 5.0
        assert srv.degrade.threshold == 2
        _, body = _post(
            srv.address,
            {"text": "hi", "max_new_tokens": 6, "temperature": 0.0},
        )
        hits["gen"] = body
        hits["health"] = _health(srv.address)
        hits["metrics"] = _get(srv.address, "/metrics")[1]

    orig = run_cli._serve_http
    monkeypatch.setattr(
        run_cli, "_serve_http",
        lambda *a, **kw: orig(*a, **kw, _test_hook=hook),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--http", "0", "--max-gen-len", "8",
         "--temperature", "0.0",
         "--inject-faults", "paged_kernel~1.0:error",
         "--quarantine-threshold", "2", "--quarantine-cooldown-s", "600",
         "--drain-timeout-s", "5"],
    )
    run_cli.main()
    out = capsys.readouterr().out
    assert "faults_armed" in out  # the StructuredLogger line
    assert len(hits["gen"]["tokens"]) == 6
    assert hits["health"]["ok"] is True
    assert hits["health"]["quarantined"] == ["paged_kernel"]
    assert "llm_feature_quarantined_paged_kernel 1" in hits["metrics"]
    # hook cleared on exit — later traces must not feed a dead drill
    assert faults_mod._trace_hook is None


# ---------------------------------------------------------------------------
# Full chaos drill (make chaos): every site in one server lifetime
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_drill_all_sites(tmp_path, capsys, monkeypatch):
    """run.py --inject-faults over every site — the generic ones (step /
    insert / alloc recover, suffix_insert feeds prefix_cache) and the
    kernel sites (flash via --attn auto prefill, paged via decode) —
    in one server lifetime: every request completes, the server ends
    degraded-but-ok, and the counters account for every injection."""
    import sys

    import jax_llama_tpu.run as run_cli
    from jax_llama_tpu.convert.checkpoint import save_checkpoint

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=128,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    spec = ",".join([
        "insert@1:error",        # a later batched prefill dispatch
        "step@6:error",          # mid-decode
        "alloc@5:oom",           # a block allocation
        "suffix_insert@0:error",  # prefix-cache hit admission
        "flash_kernel@4:error",  # flash prefill (attn auto)
        "paged_kernel@9:error",  # kernel decode step
        "step@14:nan",           # non-finite guard
    ])
    hits = {}

    def hook(srv):
        base = [int(t) for t in
                np.random.RandomState(0).randint(1, 500, size=40)]
        prompts = (
            [[5, 17, 99, 3], base + [3], base + [9]]
            + [[7 + i, 8, 9] for i in range(5)]
        )
        results = []
        for p in prompts:
            try:
                results.append(_post(
                    srv.address, {"prompt": p, "max_new_tokens": 6},
                )[1]["tokens"])
            except urllib.error.HTTPError as e:
                results.append((e.code, json.loads(e.read())["error"]))
        hits["results"] = results
        hits["health"] = _health(srv.address)
        hits["metrics"] = _get(srv.address, "/metrics")[1]

    orig = run_cli._serve_http
    monkeypatch.setattr(
        run_cli, "_serve_http",
        lambda *a, **kw: orig(*a, **kw, _test_hook=hook),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--http", "0", "--attn", "auto",
         "--max-gen-len", "8",
         "--temperature", "0.0", "--inject-faults", spec,
         "--max-recoveries", "10",
         "--quarantine-threshold", "3", "--watchdog-s", "30"],
    )
    run_cli.main()
    assert "faults_armed" in capsys.readouterr().out
    ok = [r for r in hits["results"] if isinstance(r, list)]
    failed = [r for r in hits["results"] if not isinstance(r, list)]
    # Every request either completed with its full budget or was the
    # nan-poisoned one (clean 500) — never a hang, never a 503 drain.
    assert all(len(r) == 6 for r in ok)
    assert all(code == 500 and "non-finite" in msg
               for code, msg in failed)
    assert len(failed) <= 1
    h = hits["health"]
    assert h["loop_alive"] is True
    m = hits["metrics"]
    assert "llm_faults_injected_total" in m
    total = next(
        float(line.split()[1]) for line in m.splitlines()
        if line.startswith("llm_faults_injected_total ")
    )
    assert total >= 5  # error/oom injections all fired
    assert "llm_fault_nans_armed_total 1" in m
