"""Speculative decoding: greedy verification must reproduce plain greedy
decode EXACTLY, for any draft model — the draft controls speed only."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.spec_decode import generate_speculative

TARGET = dict(
    vocab_size=128, dim=64, n_layers=3, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=256, dtype="float32", param_dtype="float32",
)
DRAFT = dict(TARGET, dim=32, n_layers=1, n_heads=2, n_kv_heads=1)


def _models(seed_t=0, seed_d=1):
    tc = get_config("tiny", **TARGET)
    dc = get_config("tiny", **DRAFT)
    tp = init_params(jax.random.PRNGKey(seed_t), tc)
    dp = init_params(jax.random.PRNGKey(seed_d), dc)
    return tp, tc, dp, dc


def _prompts(rng, B=3, P=12):
    tokens = np.full((B, P), 0, dtype=np.int32)
    mask = np.zeros((B, P), dtype=bool)
    for b in range(B):
        n = rng.randint(3, P + 1)
        tokens[b, P - n:] = rng.randint(1, 128, size=n)
        mask[b, P - n:] = True
    return jnp.asarray(tokens), jnp.asarray(mask)


@pytest.mark.parametrize("n_draft", [1, 3, 5])
def test_speculative_equals_plain_greedy(n_draft):
    tp, tc, dp, dc = _models()
    tokens, mask = _prompts(np.random.RandomState(0))
    gc = GenerationConfig(max_new_tokens=24, temperature=0.0, stop_tokens=())
    want = np.asarray(
        generate(tp, tokens, mask, jax.random.PRNGKey(0), config=tc,
                 gen_config=gc)
    )
    got, accepted = generate_speculative(
        tp, dp, tokens, mask, target_config=tc, draft_config=dc,
        gen_config=gc, n_draft=n_draft,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert (np.asarray(accepted) >= 0).all()


def test_speculative_with_stop_tokens():
    tp, tc, dp, dc = _models()
    tokens, mask = _prompts(np.random.RandomState(1))
    # Pick the token the plain decode emits first as a stop token, so the
    # stop path actually triggers.
    gc0 = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    first = int(np.asarray(
        generate(tp, tokens, mask, jax.random.PRNGKey(0), config=tc,
                 gen_config=gc0)
    )[0, tokens.shape[1] + 2])
    gc = GenerationConfig(
        max_new_tokens=16, temperature=0.0, stop_tokens=(first,), pad_id=0
    )
    want = np.asarray(
        generate(tp, tokens, mask, jax.random.PRNGKey(0), config=tc,
                 gen_config=gc)
    )
    got, _ = generate_speculative(
        tp, dp, tokens, mask, target_config=tc, draft_config=dc,
        gen_config=gc, n_draft=3,
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_self_draft_high_acceptance():
    """Draft == target: every draft token matches, acceptance ~= G per
    round, and output still equals plain greedy."""
    tp, tc, _, _ = _models()
    tokens, mask = _prompts(np.random.RandomState(2))
    gc = GenerationConfig(max_new_tokens=20, temperature=0.0, stop_tokens=())
    want = np.asarray(
        generate(tp, tokens, mask, jax.random.PRNGKey(0), config=tc,
                 gen_config=gc)
    )
    got, accepted = generate_speculative(
        tp, tp, tokens, mask, target_config=tc, draft_config=tc,
        gen_config=gc, n_draft=4,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    # 20 tokens, 4 drafts/round, perfect acceptance -> ~4 rounds, 15-16
    # accepted draft tokens.  (A draft-cache hole at d_G once cost ~3 of
    # these — this threshold guards that regression.)
    assert (np.asarray(accepted) >= 14).all(), np.asarray(accepted)


def test_speculative_sampling_requires_rng():
    tp, tc, dp, dc = _models()
    tokens, mask = _prompts(np.random.RandomState(3))
    gc = GenerationConfig(max_new_tokens=8, temperature=0.7)
    with pytest.raises(ValueError, match="rng"):
        generate_speculative(
            tp, dp, tokens, mask, target_config=tc, draft_config=dc,
            gen_config=gc,
        )


# slow (r06 budget rebalance): statistical distribution test (~14 s) —
# the same class PR 2 moved to the slow tier; the exactness contracts
# stay in tier-1 via the token-identity tests around it.
@pytest.mark.slow
def test_speculative_sampling_preserves_distribution():
    """Rejection-sampled verification must reproduce the target's sampling
    distribution: compare the empirical marginal of the first *verified*
    token (position 2) between speculative and plain sampled decode over
    many seeds.  Tiny vocab keeps the TV-distance estimate tight."""
    small = dict(
        vocab_size=16, dim=32, n_layers=2, n_heads=2, n_kv_heads=1,
        multiple_of=32, max_seq_len=64, dtype="float32",
        param_dtype="float32",
    )
    tc = get_config("tiny", **small)
    dc = get_config("tiny", **{**small, "dim": 16, "n_layers": 1})
    tp = init_params(jax.random.PRNGKey(0), tc)
    dp = init_params(jax.random.PRNGKey(1), dc)
    tokens = jnp.asarray([[3, 5, 7, 11]], jnp.int32)
    mask = jnp.ones((1, 4), bool)
    gc = GenerationConfig(max_new_tokens=3, temperature=0.9, top_p=None,
                          stop_tokens=())
    P = tokens.shape[1]
    n_seeds = 1500

    def spec_tok(key):
        out, _ = generate_speculative(
            tp, dp, tokens, mask, key, target_config=tc, draft_config=dc,
            gen_config=gc, n_draft=2,
        )
        return out[0, P + 1]  # first token produced by verification

    def plain_tok(key):
        out = generate(tp, tokens, mask, key, config=tc, gen_config=gc)
        return out[0, P + 1]

    keys = jax.random.split(jax.random.PRNGKey(42), n_seeds)
    spec = np.asarray(jax.lax.map(spec_tok, keys, batch_size=64))
    plain = np.asarray(jax.lax.map(plain_tok, keys, batch_size=64))
    V = small["vocab_size"]
    h_spec = np.bincount(spec, minlength=V) / n_seeds
    h_plain = np.bincount(plain, minlength=V) / n_seeds
    tv = 0.5 * np.abs(h_spec - h_plain).sum()
    # TV noise floor for two empirical estimates at n=1500, V=16 is ~0.05.
    assert tv < 0.12, (tv, h_spec, h_plain)


def test_speculative_rejects_vocab_mismatch():
    tp, tc, _, _ = _models()
    dc2 = get_config("tiny", **{**DRAFT, "vocab_size": 64})
    dp2 = init_params(jax.random.PRNGKey(1), dc2)
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0)
    tokens, mask = _prompts(np.random.RandomState(4))
    with pytest.raises(ValueError, match="vocab"):
        generate_speculative(
            tp, dp2, tokens, mask, target_config=tc, draft_config=dc2,
            gen_config=gc,
        )


def test_speculative_with_int8_kv_cache():
    """Spec decode must carry the int8 cache's scale leaves through the
    while_loop (a KVCache rebuild once dropped them -> trace error)."""
    tc = get_config("tiny", **{**TARGET, "kv_cache_dtype": "int8"})
    dc = get_config("tiny", **{**DRAFT, "kv_cache_dtype": "int8"})
    tp = init_params(jax.random.PRNGKey(0), tc)
    dp = init_params(jax.random.PRNGKey(1), dc)
    tokens, mask = _prompts(np.random.RandomState(5))
    gc = GenerationConfig(max_new_tokens=12, temperature=0.0, stop_tokens=())
    got, _ = generate_speculative(
        tp, dp, tokens, mask, target_config=tc, draft_config=dc,
        gen_config=gc, n_draft=3,
    )
    # int8 cache perturbs logits slightly, so only shape/validity checked
    # (exact greedy equality is asserted on the fp cache path).
    o = np.asarray(got)
    assert o.shape == (3, tokens.shape[1] + 12)
    assert (o >= 0).all() and (o < 128).all()
