"""Flash-attention kernel parity vs the XLA reference path.

The Pallas kernel runs in interpret mode on the CPU test mesh; parity vs
``ops.attention.sdpa`` (itself oracle-checked in test_ops/test_model) at
fp32 tolerances covers the online-softmax math, GQA index mapping,
positional masking, and tile-padding logic.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.models import forward
from jax_llama_tpu.ops import attention_bias, flash_attention, sdpa


def _ref(q, k, v, q_pos, kv_pos):
    bias = attention_bias(
        jnp.asarray(q_pos), jnp.asarray(kv_pos), jnp.asarray(kv_pos) >= 0
    )
    return np.asarray(
        sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias)
    )


def _rand(B, T, S, H, KVH, D):
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, S, KVH, D).astype(np.float32)
    v = np.random.randn(B, S, KVH, D).astype(np.float32)
    return q, k, v


def test_flash_matches_sdpa_causal():
    B, T, H, KVH, D = 2, 24, 4, 2, 16
    q, k, v = _rand(B, T, T, H, KVH, D)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, pos, pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_triangular_diagonal_body():
    """The ragged diagonal body (r5): active when block_q/_KSUB is
    sublane-aligned — (32, 64) tiles here — on every causal crossing
    tile.  Parity vs sdpa with GQA + left-padding, gradient parity, and
    the dynamic triangle-safety fallback under a SHUFFLED kv layout
    (non-ascending positions must route to the uniform masked body and
    still be exact)."""
    import jax

    B, T, H, KVH, D = 2, 160, 4, 2, 64
    rng = np.random.RandomState(11)
    q = rng.randn(B, T, H, D).astype(np.float32) * 0.3
    k = rng.randn(B, T, KVH, D).astype(np.float32) * 0.3
    v = rng.randn(B, T, KVH, D).astype(np.float32) * 0.3
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    pos[1, :9] = -1
    pos[1, 9:] = np.arange(T - 9)
    qp = np.maximum(pos, 0)

    def fl(q, k, v, kv_pos):
        return flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(qp), jnp.asarray(kv_pos),
            block_q=32, block_k=64,
        )

    got = np.asarray(fl(q, k, v, pos))
    want = _ref(q, k, v, qp, pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)

    # Gradients flow through the ragged body (fwd saves lse; backward
    # kernels are tile-uniform — consistency across the pair is what
    # this pins).
    g = rng.randn(B, T, H, D).astype(np.float32)
    f_out, f_vjp = jax.vjp(
        lambda a, b, c: fl(a, b, c, pos),
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
    )

    def dense(a, b, c):
        bias = attention_bias(
            jnp.asarray(qp), jnp.asarray(pos), jnp.asarray(pos) >= 0
        )
        return sdpa(a, b, c, bias)

    d_out, d_vjp = jax.vjp(
        dense, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    for fg, dg, name in zip(
        f_vjp(jnp.asarray(g)), d_vjp(jnp.asarray(g)), ("dq", "dk", "dv")
    ):
        denom = max(np.abs(np.asarray(dg)).max(), 1e-6)
        assert np.abs(np.asarray(fg) - np.asarray(dg)).max() / denom < 2e-3, name

    # Shuffled kv layout: positions non-ascending, triangle safety must
    # reject the ragged body tile-by-tile; result stays exact.
    perm = rng.permutation(T)
    got_sh = np.asarray(fl(q, k[:, perm], v[:, perm], pos[:, perm]))
    want_sh = _ref(q, k[:, perm], v[:, perm], qp, pos[:, perm])
    np.testing.assert_allclose(got_sh, want_sh, atol=1e-5, rtol=1e-4)


def test_flash_non_multiple_block_sizes():
    # T=13, S=21 not multiples of the 8/16 tiles: exercises the padding path.
    B, T, S, H, KVH, D = 1, 13, 21, 4, 4, 8
    q, k, v = _rand(B, T, S, H, KVH, D)
    q_pos = np.tile(np.arange(S - T, S, dtype=np.int32), (B, 1))
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=16,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_padding_and_cache_slots_masked():
    # Left-padded prompt (slots -1) plus unwritten cache tail (slots -1):
    # the decode-over-cache geometry.
    B, T, S, H, KVH, D = 2, 4, 32, 4, 2, 8
    q, k, v = _rand(B, T, S, H, KVH, D)
    kv_pos = np.full((B, S), -1, dtype=np.int32)
    kv_pos[:, 2:10] = np.arange(8)  # 8 valid slots mid-cache
    q_pos = np.tile(np.arange(4, 8, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_single_query_decode_shape():
    # T=1 (decode step): the kernel must handle a 1-row q block.
    B, S, H, KVH, D = 2, 40, 8, 2, 16
    q, k, v = _rand(B, 1, S, H, KVH, D)
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    kv_pos[:, 30:] = -1
    q_pos = np.full((B, 1), 29, dtype=np.int32)
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_quantized_matches_dequantized_reference():
    """flash_attention_quantized's in-kernel scale folding must equal
    dense attention over the explicitly dequantized K/V (scales are
    constant along head_dim, so the folding is exact up to fp order)."""
    from jax_llama_tpu.models.llama import quantize_kv
    from jax_llama_tpu.ops import flash_attention_quantized

    B, T, S, H, KVH, D = 2, 12, 24, 4, 2, 16
    q, k, v = _rand(B, T, S, H, KVH, D)
    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    kv_pos[:, 20:] = -1  # unwritten tail
    q_pos = np.tile(np.arange(S - T - 4, S - 4, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention_quantized(
            jnp.asarray(q), kq, vq, ks, vs,
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    k_deq = np.asarray(kq, np.float32) * np.asarray(ks)[..., None]
    v_deq = np.asarray(vq, np.float32) * np.asarray(vs)[..., None]
    want = _ref(q, k_deq, v_deq, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_model_forward_flash_matches_xla():
    import jax

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 18
    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ref_logits, _ = forward(params, tokens, positions, config)
    flash_logits, _ = forward(
        params, tokens, positions, config.replace(attn_impl="flash")
    )
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(ref_logits), atol=2e-4, rtol=1e-4
    )


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_model_decode_with_cache_flash_matches_xla():
    import jax
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    B, P = 2, 9
    prompt = np.random.randint(1, config.vocab_size, (B, P)).astype(np.int32)
    mask = np.ones((B, P), dtype=bool)
    mask[0, :3] = False  # left padding on row 0
    prompt[0, :3] = 0
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    key = jax.random.PRNGKey(1)
    out_ref = generate(
        params, jnp.asarray(prompt), jnp.asarray(mask), key,
        config=config, gen_config=gc,
    )
    out_flash = generate(
        params, jnp.asarray(prompt), jnp.asarray(mask), key,
        config=config.replace(attn_impl="flash"), gen_config=gc,
    )
    np.testing.assert_array_equal(np.asarray(out_ref), np.asarray(out_flash))


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_gradients_match_xla():
    import jax

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    from jax_llama_tpu.train import lm_loss

    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (2, 16)), jnp.int32
    )
    l0, g0 = jax.value_and_grad(lm_loss)(params, tokens, config)
    l1, g1 = jax.value_and_grad(lm_loss)(
        params, tokens, config.replace(attn_impl="flash")
    )
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        ),
        g1, g0,
    )


# ---------------------------------------------------------------------------
# Blockwise backward kernels (dQ / dK / dV with recomputed probabilities)
# ---------------------------------------------------------------------------

def _vjps(q, k, v, q_pos, kv_pos, g, bq, bk):
    import jax

    q, k, v, g = map(jnp.asarray, (q, k, v, g))
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, q_pos, kv_pos, block_q=bq, block_k=bk)

    def dense_fn(q, k, v):
        return sdpa(q, k, v, attention_bias(q_pos, kv_pos, kv_pos >= 0))

    _, fvjp = jax.vjp(flash_fn, q, k, v)
    _, dvjp = jax.vjp(dense_fn, q, k, v)
    return fvjp(g), dvjp(g)


def test_flash_backward_matches_dense_gqa_and_padding():
    B, T, H, KVH, D = 2, 24, 4, 2, 16
    q, k, v = _rand(B, T, T, H, KVH, D)
    # Realistic left-pad geometry (engine.prompt_positions): padded slots
    # carry -1 and real positions restart at 0.  (Fully-masked rows are
    # out of scope: their forward output is unspecified garbage on both
    # paths, so their cotangents are too.)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    pos[1, :5] = -1
    pos[1, 5:] = np.arange(T - 5)
    qp = np.maximum(pos, 0)
    g = np.random.randn(B, T, H, D).astype(np.float32)
    g[1, :5] = 0.0  # pad rows are masked downstream; no cotangent flows
    (fdq, fdk, fdv), (ddq, ddk, ddv) = _vjps(q, k, v, qp, pos, g, 8, 8)
    np.testing.assert_allclose(np.asarray(fdq), np.asarray(ddq), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fdk), np.asarray(ddk), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fdv), np.asarray(ddv), atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_backward_matches_dense_8k():
    """Long-context gradient parity at the production block sizes
    (VERDICT r1 item 4).  Small head count keeps the dense oracle's S^2
    buffers manageable in interpret mode."""
    B, S, H, D = 1, 8192, 1, 64
    q, k, v = _rand(B, S, S, H, H, D)
    pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    g = np.random.randn(B, S, H, D).astype(np.float32)
    (fdq, fdk, fdv), (ddq, ddk, ddv) = _vjps(q, k, v, pos, pos, g, 512, 2048)
    for f, dref, name in ((fdq, ddq, "dq"), (fdk, ddk, "dk"), (fdv, ddv, "dv")):
        f, dref = np.asarray(f), np.asarray(dref)
        denom = np.abs(dref).max()
        assert np.abs(f - dref).max() / denom < 1e-4, name


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_backward_fdiff_16k():
    """At 16k a dense oracle no longer fits; check the analytic gradient
    against a central finite difference along a random direction."""
    import jax

    B, S, H, D = 1, 16384, 1, 32
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    k = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    v = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    pos = jnp.asarray(np.tile(np.arange(S, dtype=np.int32), (B, 1)))
    w = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    def loss(k):
        o = flash_attention(jnp.asarray(q), k, jnp.asarray(v), pos, pos)
        return jnp.vdot(o, w)

    gk = jax.grad(loss)(jnp.asarray(k))
    u = rng.randn(*k.shape).astype(np.float32)
    u /= np.linalg.norm(u)
    eps = 1e-2
    lo = float(loss(jnp.asarray(k - eps * u)))
    hi = float(loss(jnp.asarray(k + eps * u)))
    fdiff = (hi - lo) / (2 * eps)
    analytic = float(jnp.vdot(gk, jnp.asarray(u)))
    np.testing.assert_allclose(analytic, fdiff, rtol=2e-2, atol=1e-3)


def test_flash_backward_no_quadratic_memory_32k():
    """The whole point of the kernel: no S x S intermediate anywhere in the
    VJP jaxpr at 32k (the r1 dense fallback materialized [B, H, T, S])."""
    import jax

    B, S, H, D = 1, 32768, 1, 64

    def loss(q, k, v):
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        return flash_attention(q, k, v, pos, pos).sum()

    sds = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32)
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(sds, sds, sds)

    limit = S * 1024  # O(S*d) with the lane-replicated lse/delta rows
    def walk(jpr):
        for eqn in jpr.eqns:
            for var in eqn.outvars:
                size = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                assert size <= limit, (eqn.primitive.name, var.aval.shape)
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)
    walk(jaxpr.jaxpr)


# ---------------------------------------------------------------------------
# In-kernel attention-probability dropout (attn_pdrop on the flash path).
#
# There is no PRNG-bit parity to check against the xla path (different
# generators by design), so the tests pin down the *semantics*: the realized
# mask is Bernoulli with the right rate, scaled by 1/(1-rate), identical
# across tilings and calls, and the backward kernels reproduce the exact
# forward draw (gradient parity vs a dense model built from the EXTRACTED
# mask — any fwd/bwd mask drift would show up at O(1), not 1e-4).
# ---------------------------------------------------------------------------


def _extract_dropout_weights(q, k, q_pos, kv_pos, rate, seed, bq, bk):
    """Run the kernel with v = identity basis so row i of the output IS the
    post-dropout weight row u_i = D_i * softmax(s)_i (needs d >= S)."""
    B, T, H, d = q.shape
    S = k.shape[1]
    assert d >= S and H == k.shape[2]
    v = jnp.zeros((B, S, H, d), jnp.float32)
    eye = jnp.arange(S)
    for b in range(B):
        for h in range(H):
            v = v.at[b, eye, h, eye].set(1.0)
    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k), v, jnp.asarray(q_pos),
        jnp.asarray(kv_pos), block_q=bq, block_k=bk,
        dropout_rate=rate, dropout_seed=seed,
    )
    return np.asarray(out[..., :S])  # [B, T, H, S] realized u


def _dense_weights(q, k, q_pos, kv_pos):
    import jax

    s = jnp.einsum("bthd,bshd->bths", jnp.asarray(q), jnp.asarray(k))
    s = s / np.sqrt(q.shape[-1])
    allowed = (
        (jnp.asarray(kv_pos)[:, None, None, :]
         <= jnp.asarray(q_pos)[:, :, None, None])
        & (jnp.asarray(kv_pos) >= 0)[:, None, None, :]
    )
    s = jnp.where(allowed, s, -1e30)
    return np.asarray(jax.nn.softmax(s, axis=-1)), np.asarray(allowed)


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_dropout_mask_is_inverted_bernoulli():
    B, T, S, H, d = 1, 64, 64, 2, 64
    rng = np.random.RandomState(3)
    q = rng.randn(B, T, H, d).astype(np.float32) * 0.2
    k = rng.randn(B, S, H, d).astype(np.float32) * 0.2
    pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    rate = 0.25
    seed = jnp.asarray([77], jnp.uint32)
    u = _extract_dropout_weights(q, k, pos, pos, rate, seed, 16, 16)
    w, allowed = _dense_weights(q, k, pos, pos)
    resolvable = allowed & (w > 1e-3)
    D = u[resolvable] / w[resolvable]
    keep_val = 1.0 / (1.0 - rate)
    is_kept = np.abs(D - keep_val) < 1e-2
    is_dropped = np.abs(D) < 1e-2
    assert np.all(is_kept | is_dropped)  # binary inverted-dropout values
    frac = is_dropped.mean()
    assert abs(frac - rate) < 0.05, frac  # ~Bernoulli(rate)
    # Tile-size invariance: the mask hashes GLOBAL (row, col) indices, so
    # retiling must not change the draw.
    u2 = _extract_dropout_weights(q, k, pos, pos, rate, seed, 32, 64)
    np.testing.assert_allclose(u, u2, atol=1e-5)
    # Seed sensitivity + per-head independence.
    u3 = _extract_dropout_weights(
        q, k, pos, pos, rate, jnp.asarray([78], jnp.uint32), 16, 16
    )
    assert np.abs(u - u3).max() > 0.1
    # The seed is 64-bit: the HIGH word must drive an independent draw
    # (a [1] seed widens to a zero high word, so [77, 1] != [77]).
    u_hi = _extract_dropout_weights(
        q, k, pos, pos, rate, jnp.asarray([77, 1], jnp.uint32), 16, 16
    )
    assert np.abs(u - u_hi).max() > 0.1
    D_full = np.where(w > 1e-3, u / np.maximum(w, 1e-30), 0.0)
    assert np.abs(D_full[0, :, 0] - D_full[0, :, 1]).max() > 0.1


def test_flash_dropout_rate0_and_seed_requirements():
    B, T, H, D = 1, 16, 2, 32
    q, k, v = _rand(B, T, T, H, H, D)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    base = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pos), jnp.asarray(pos), block_q=8, block_k=8,
    )
    with_seed = flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(pos), jnp.asarray(pos), block_q=8, block_k=8,
        dropout_rate=0.0, dropout_seed=jnp.asarray([5], jnp.uint32),
    )
    np.testing.assert_array_equal(np.asarray(base), np.asarray(with_seed))
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos), dropout_rate=0.5,
        )


@pytest.mark.slow  # interpret-mode Pallas / long decode on CPU; out of the tier-1 budget (plain `pytest tests/` still runs it)
def test_flash_dropout_backward_matches_dense_with_extracted_mask():
    """Gradient parity for q/k/v against a dense attention whose dropout
    matrix is the mask EXTRACTED from the kernel forward: proves all three
    kernels (fwd, dQ, dK/dV) regenerate the same draw, including under GQA
    query packing and left-padding."""
    import jax

    B, T, S, H, KVH, d = 2, 40, 40, 4, 2, 64
    rng = np.random.RandomState(5)
    q = rng.randn(B, T, H, d).astype(np.float32) * 0.2
    k = rng.randn(B, S, KVH, d).astype(np.float32) * 0.2
    v = rng.randn(B, S, KVH, d).astype(np.float32) * 0.2
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    pos[1, :7] = -1
    pos[1, 7:] = np.arange(T - 7)
    qp = np.maximum(pos, 0)
    rate, seed = 0.3, jnp.asarray([123], jnp.uint32)
    g = rng.randn(B, T, H, d).astype(np.float32)
    g[1, :7] = 0.0

    # Extract the realized per-(b, kv-head, packed-row, col) mask by
    # running the PACKED single-group geometry the kernel actually uses.
    group = H // KVH
    q_packed = np.moveaxis(
        q.reshape(B, T, KVH, group, d), 3, 1
    ).reshape(B, group * T, KVH, d)
    qp_packed = np.tile(qp, (1, group))
    u = _extract_dropout_weights(
        q_packed, k, qp_packed, pos, rate, seed, 16, 16
    )  # [B, group*T, KVH, S]
    w, allowed = _dense_weights(q_packed, k, qp_packed, pos)
    keep_val = 1.0 / (1.0 - rate)
    D = np.where(
        allowed & (w > 1e-4),
        np.rint(u / np.maximum(w, 1e-30) / keep_val) * keep_val,
        # Unresolvable (w ~ 0) entries contribute ~nothing to outputs or
        # grads either way; call them kept.
        keep_val,
    ).astype(np.float32)
    D = jnp.asarray(D)  # [B, group*T, KVH, S] packed-row dropout matrix

    def dense_fn(q, k, v):
        qp_j = jnp.moveaxis(
            q.reshape(B, T, KVH, group, d), 3, 1
        ).reshape(B, group * T, KVH, d)
        s = jnp.einsum("bthd,bshd->bths", qp_j, k) / np.sqrt(d)
        s = jnp.where(
            (jnp.asarray(pos)[:, None, None, :]
             <= jnp.asarray(qp_packed)[:, :, None, None])
            & (jnp.asarray(pos) >= 0)[:, None, None, :],
            s, -1e30,
        )
        ww = jax.nn.softmax(s, axis=-1) * D
        o = jnp.einsum("bths,bshd->bthd", ww, v)
        return jnp.moveaxis(
            o.reshape(B, group, T, KVH, d), 1, 3
        ).reshape(B, T, H, d)

    def flash_fn(q, k, v):
        return flash_attention(
            jnp.asarray(q), k, v, jnp.asarray(qp), jnp.asarray(pos),
            block_q=16, block_k=16, dropout_rate=rate, dropout_seed=seed,
        )

    fout, fvjp = jax.vjp(flash_fn, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    dout, dvjp = jax.vjp(dense_fn, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(
        np.asarray(fout)[0], np.asarray(dout)[0], atol=1e-4, rtol=1e-3
    )
    for f, dref, name in zip(fvjp(jnp.asarray(g)), dvjp(jnp.asarray(g)),
                             ("dq", "dk", "dv")):
        f, dref = np.asarray(f), np.asarray(dref)
        denom = max(np.abs(dref).max(), 1e-6)
        assert np.abs(f - dref).max() / denom < 2e-3, name


def test_flash_dropout_no_quadratic_memory_32k():
    """Dropout must not break the O(S*d) guarantee: the mask lives only as
    [block_q, block_k] tiles inside the kernels."""
    import jax

    B, S, H, D = 1, 32768, 1, 64

    def loss(q, k, v):
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        return flash_attention(
            q, k, v, pos, pos, dropout_rate=0.1,
            dropout_seed=jnp.asarray([9], jnp.uint32),
        ).sum()

    sds = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32)
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(sds, sds, sds)

    limit = S * 1024
    def walk(jpr):
        for eqn in jpr.eqns:
            for var in eqn.outvars:
                size = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                assert size <= limit, (eqn.primitive.name, var.aval.shape)
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)
    walk(jaxpr.jaxpr)
