"""Flash-attention kernel parity vs the XLA reference path.

The Pallas kernel runs in interpret mode on the CPU test mesh; parity vs
``ops.attention.sdpa`` (itself oracle-checked in test_ops/test_model) at
fp32 tolerances covers the online-softmax math, GQA index mapping,
positional masking, and tile-padding logic.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.models import forward
from jax_llama_tpu.ops import attention_bias, flash_attention, sdpa


def _ref(q, k, v, q_pos, kv_pos):
    bias = attention_bias(
        jnp.asarray(q_pos), jnp.asarray(kv_pos), jnp.asarray(kv_pos) >= 0
    )
    return np.asarray(
        sdpa(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), bias)
    )


def _rand(B, T, S, H, KVH, D):
    q = np.random.randn(B, T, H, D).astype(np.float32)
    k = np.random.randn(B, S, KVH, D).astype(np.float32)
    v = np.random.randn(B, S, KVH, D).astype(np.float32)
    return q, k, v


def test_flash_matches_sdpa_causal():
    B, T, H, KVH, D = 2, 24, 4, 2, 16
    q, k, v = _rand(B, T, T, H, KVH, D)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pos), jnp.asarray(pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, pos, pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_non_multiple_block_sizes():
    # T=13, S=21 not multiples of the 8/16 tiles: exercises the padding path.
    B, T, S, H, KVH, D = 1, 13, 21, 4, 4, 8
    q, k, v = _rand(B, T, S, H, KVH, D)
    q_pos = np.tile(np.arange(S - T, S, dtype=np.int32), (B, 1))
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=16,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_padding_and_cache_slots_masked():
    # Left-padded prompt (slots -1) plus unwritten cache tail (slots -1):
    # the decode-over-cache geometry.
    B, T, S, H, KVH, D = 2, 4, 32, 4, 2, 8
    q, k, v = _rand(B, T, S, H, KVH, D)
    kv_pos = np.full((B, S), -1, dtype=np.int32)
    kv_pos[:, 2:10] = np.arange(8)  # 8 valid slots mid-cache
    q_pos = np.tile(np.arange(4, 8, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_single_query_decode_shape():
    # T=1 (decode step): the kernel must handle a 1-row q block.
    B, S, H, KVH, D = 2, 40, 8, 2, 16
    q, k, v = _rand(B, 1, S, H, KVH, D)
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    kv_pos[:, 30:] = -1
    q_pos = np.full((B, 1), 29, dtype=np.int32)
    got = np.asarray(
        flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    want = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_quantized_matches_dequantized_reference():
    """flash_attention_quantized's in-kernel scale folding must equal
    dense attention over the explicitly dequantized K/V (scales are
    constant along head_dim, so the folding is exact up to fp order)."""
    from jax_llama_tpu.models.llama import quantize_kv
    from jax_llama_tpu.ops import flash_attention_quantized

    B, T, S, H, KVH, D = 2, 12, 24, 4, 2, 16
    q, k, v = _rand(B, T, S, H, KVH, D)
    kq, ks = quantize_kv(jnp.asarray(k))
    vq, vs = quantize_kv(jnp.asarray(v))
    kv_pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    kv_pos[:, 20:] = -1  # unwritten tail
    q_pos = np.tile(np.arange(S - T - 4, S - 4, dtype=np.int32), (B, 1))
    got = np.asarray(
        flash_attention_quantized(
            jnp.asarray(q), kq, vq, ks, vs,
            jnp.asarray(q_pos), jnp.asarray(kv_pos), block_q=8, block_k=8,
        )
    )
    k_deq = np.asarray(kq, np.float32) * np.asarray(ks)[..., None]
    v_deq = np.asarray(vq, np.float32) * np.asarray(vs)[..., None]
    want = _ref(q, k_deq, v_deq, q_pos, kv_pos)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_model_forward_flash_matches_xla():
    import jax

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    B, T = 2, 18
    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (B, T)), jnp.int32
    )
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    ref_logits, _ = forward(params, tokens, positions, config)
    flash_logits, _ = forward(
        params, tokens, positions, config.replace(attn_impl="flash")
    )
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(ref_logits), atol=2e-4, rtol=1e-4
    )


def test_model_decode_with_cache_flash_matches_xla():
    import jax
    from jax_llama_tpu.engine import GenerationConfig, generate

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    B, P = 2, 9
    prompt = np.random.randint(1, config.vocab_size, (B, P)).astype(np.int32)
    mask = np.ones((B, P), dtype=bool)
    mask[0, :3] = False  # left padding on row 0
    prompt[0, :3] = 0
    gc = GenerationConfig(max_new_tokens=8, temperature=0.0, stop_tokens=())
    key = jax.random.PRNGKey(1)
    out_ref = generate(
        params, jnp.asarray(prompt), jnp.asarray(mask), key,
        config=config, gen_config=gc,
    )
    out_flash = generate(
        params, jnp.asarray(prompt), jnp.asarray(mask), key,
        config=config.replace(attn_impl="flash"), gen_config=gc,
    )
    np.testing.assert_array_equal(np.asarray(out_ref), np.asarray(out_flash))


def test_flash_gradients_match_xla():
    import jax

    config = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), config)
    from jax_llama_tpu.train import lm_loss

    tokens = jnp.asarray(
        np.random.randint(0, config.vocab_size, (2, 16)), jnp.int32
    )
    l0, g0 = jax.value_and_grad(lm_loss)(params, tokens, config)
    l1, g1 = jax.value_and_grad(lm_loss)(
        params, tokens, config.replace(attn_impl="flash")
    )
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        ),
        g1, g0,
    )


# ---------------------------------------------------------------------------
# Blockwise backward kernels (dQ / dK / dV with recomputed probabilities)
# ---------------------------------------------------------------------------

def _vjps(q, k, v, q_pos, kv_pos, g, bq, bk):
    import jax

    q, k, v, g = map(jnp.asarray, (q, k, v, g))
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)

    def flash_fn(q, k, v):
        return flash_attention(q, k, v, q_pos, kv_pos, block_q=bq, block_k=bk)

    def dense_fn(q, k, v):
        return sdpa(q, k, v, attention_bias(q_pos, kv_pos, kv_pos >= 0))

    _, fvjp = jax.vjp(flash_fn, q, k, v)
    _, dvjp = jax.vjp(dense_fn, q, k, v)
    return fvjp(g), dvjp(g)


def test_flash_backward_matches_dense_gqa_and_padding():
    B, T, H, KVH, D = 2, 24, 4, 2, 16
    q, k, v = _rand(B, T, T, H, KVH, D)
    # Realistic left-pad geometry (engine.prompt_positions): padded slots
    # carry -1 and real positions restart at 0.  (Fully-masked rows are
    # out of scope: their forward output is unspecified garbage on both
    # paths, so their cotangents are too.)
    pos = np.tile(np.arange(T, dtype=np.int32), (B, 1))
    pos[1, :5] = -1
    pos[1, 5:] = np.arange(T - 5)
    qp = np.maximum(pos, 0)
    g = np.random.randn(B, T, H, D).astype(np.float32)
    g[1, :5] = 0.0  # pad rows are masked downstream; no cotangent flows
    (fdq, fdk, fdv), (ddq, ddk, ddv) = _vjps(q, k, v, qp, pos, g, 8, 8)
    np.testing.assert_allclose(np.asarray(fdq), np.asarray(ddq), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fdk), np.asarray(ddk), atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(fdv), np.asarray(ddv), atol=1e-4, rtol=1e-4)


def test_flash_backward_matches_dense_8k():
    """Long-context gradient parity at the production block sizes
    (VERDICT r1 item 4).  Small head count keeps the dense oracle's S^2
    buffers manageable in interpret mode."""
    B, S, H, D = 1, 8192, 1, 64
    q, k, v = _rand(B, S, S, H, H, D)
    pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    g = np.random.randn(B, S, H, D).astype(np.float32)
    (fdq, fdk, fdv), (ddq, ddk, ddv) = _vjps(q, k, v, pos, pos, g, 512, 2048)
    for f, dref, name in ((fdq, ddq, "dq"), (fdk, ddk, "dk"), (fdv, ddv, "dv")):
        f, dref = np.asarray(f), np.asarray(dref)
        denom = np.abs(dref).max()
        assert np.abs(f - dref).max() / denom < 1e-4, name


def test_flash_backward_fdiff_16k():
    """At 16k a dense oracle no longer fits; check the analytic gradient
    against a central finite difference along a random direction."""
    import jax

    B, S, H, D = 1, 16384, 1, 32
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    k = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    v = rng.randn(B, S, H, D).astype(np.float32) * 0.1
    pos = jnp.asarray(np.tile(np.arange(S, dtype=np.int32), (B, 1)))
    w = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    def loss(k):
        o = flash_attention(jnp.asarray(q), k, jnp.asarray(v), pos, pos)
        return jnp.vdot(o, w)

    gk = jax.grad(loss)(jnp.asarray(k))
    u = rng.randn(*k.shape).astype(np.float32)
    u /= np.linalg.norm(u)
    eps = 1e-2
    lo = float(loss(jnp.asarray(k - eps * u)))
    hi = float(loss(jnp.asarray(k + eps * u)))
    fdiff = (hi - lo) / (2 * eps)
    analytic = float(jnp.vdot(gk, jnp.asarray(u)))
    np.testing.assert_allclose(analytic, fdiff, rtol=2e-2, atol=1e-3)


def test_flash_backward_no_quadratic_memory_32k():
    """The whole point of the kernel: no S x S intermediate anywhere in the
    VJP jaxpr at 32k (the r1 dense fallback materialized [B, H, T, S])."""
    import jax

    B, S, H, D = 1, 32768, 1, 64

    def loss(q, k, v):
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        return flash_attention(q, k, v, pos, pos).sum()

    sds = jax.ShapeDtypeStruct((B, S, H, D), jnp.float32)
    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(sds, sds, sds)

    limit = S * 1024  # O(S*d) with the lane-replicated lse/delta rows
    def walk(jpr):
        for eqn in jpr.eqns:
            for var in eqn.outvars:
                size = int(np.prod(var.aval.shape)) if var.aval.shape else 1
                assert size <= limit, (eqn.primitive.name, var.aval.shape)
            for sub in jax.core.jaxprs_in_params(eqn.params):
                walk(sub)
    walk(jaxpr.jaxpr)
