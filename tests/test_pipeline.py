"""Pipeline parallelism: GPipe schedule over the `stage` mesh axis must be
numerically transparent — same forward, loss, and gradients as the plain
scan stack (the reference has no pipeline parallelism at all, SURVEY.md
§2.13b; this is new capability, tested against the framework's own
single-device path as oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import skip_if_xla_partition_id_skew

from jax_llama_tpu import get_config, init_params, make_mesh
from jax_llama_tpu.models import forward
from jax_llama_tpu.parallel import shard_params, use_mesh
from jax_llama_tpu.train import init_train_state, lm_loss, make_optimizer, train_step

CFG = dict(
    vocab_size=128, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=32, dtype="float32", param_dtype="float32",
)


def _setup(stage, **mesh_axes):
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    mesh = make_mesh(stage=stage, **mesh_axes, devices=jax.devices()[: stage * int(np.prod(list(mesh_axes.values()) or [1]))])
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, config.vocab_size, (4, 16)),
        jnp.int32,
    )
    return config, params, mesh, tokens


def _reference_logits(config, params, tokens):
    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    logits, _ = forward(params, tokens, pos, config)
    return np.asarray(logits)


@pytest.mark.parametrize("stage,extra", [(2, {}), (4, {}), (2, {"tensor": 2})])
def test_pipeline_forward_matches_plain(stage, extra):
    config, params, mesh, tokens = _setup(stage, **extra)
    want = _reference_logits(config, params, tokens)

    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    sharded = shard_params(params, mesh, config)

    @jax.jit
    def run(p, t, q):
        with use_mesh(mesh):
            return forward(p, t, q, config)[0]

    try:
        got = np.asarray(run(sharded, tokens, pos))
    except Exception as e:  # noqa: BLE001 — skew-detect, re-raise the rest
        skip_if_xla_partition_id_skew(e)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_pipeline_microbatch_counts():
    config, params, mesh, tokens = _setup(2)
    want = _reference_logits(config, params, tokens)
    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    for m in (1, 2, 4):
        cfg_m = config.replace(pp_microbatches=m)

        @jax.jit
        def run(p, t, q):
            with use_mesh(mesh):
                return forward(p, t, q, cfg_m)[0]

        got = np.asarray(run(shard_params(params, mesh, cfg_m), tokens, pos))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_pipeline_respects_padding():
    """Left-padded rows (-1 positions) must mask identically under pp."""
    config, params, mesh, tokens = _setup(2)
    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    pos = pos.at[0, :5].set(-1)  # row 0: 5 pad slots
    logits, _ = forward(params, tokens, pos, config)
    want = np.asarray(logits)

    @jax.jit
    def run(p, t, q):
        with use_mesh(mesh):
            return forward(p, t, q, config)[0]

    got = np.asarray(run(shard_params(params, mesh, config), tokens, pos))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_pipeline_grads_match_plain():
    config, params, mesh, tokens = _setup(2)
    grads_plain = jax.grad(lm_loss)(params, tokens, config)

    sharded = shard_params(params, mesh, config)

    @jax.jit
    def g(p, t):
        with use_mesh(mesh):
            return jax.grad(lm_loss)(p, t, config)

    grads_pp = g(sharded, tokens)
    flat_a, _ = jax.tree.flatten(grads_plain)
    flat_b, _ = jax.tree.flatten(grads_pp)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
        )


def test_pipeline_train_step():
    config, params, mesh, tokens = _setup(2, tensor=2)
    optimizer = make_optimizer(learning_rate=1e-3)
    state = init_train_state(shard_params(params, mesh, config), optimizer)
    try:
        state, loss = train_step(state, tokens, config, optimizer, mesh=mesh)
        assert np.isfinite(float(loss))
    except AssertionError:
        raise
    except Exception as e:  # noqa: BLE001 — skew-detect, re-raise the rest
        skip_if_xla_partition_id_skew(e)
    state2, loss2 = train_step(state, tokens, config, optimizer, mesh=mesh)
    assert float(loss2) < float(loss)  # tiny model overfits one batch fast


def test_pipeline_rejects_seq_axis():
    config, params, mesh, tokens = _setup(2, seq=2)
    config = config.replace(attn_impl="ring")
    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    with pytest.raises(NotImplementedError):
        with use_mesh(mesh):
            forward(shard_params(params, mesh, config), tokens, pos, config)


def test_stage_must_divide_layers():
    config = get_config("tiny", **{**CFG, "n_layers": 3})
    params = init_params(jax.random.PRNGKey(0), config)
    mesh = make_mesh(stage=2, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="stage"):
        shard_params(params, mesh, config)


def test_pipeline_rejects_cache_decode():
    """Decode over a KV cache must refuse on a stage>1 mesh (the scan path
    would silently all-gather stage-sharded weights every step)."""
    from jax_llama_tpu.models.llama import init_cache

    config, params, mesh, tokens = _setup(2)
    cache = init_cache(config, batch=4, max_len=16)
    pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (4, 16))
    with pytest.raises(NotImplementedError, match="stage"):
        with use_mesh(mesh):
            forward(shard_params(params, mesh, config), tokens, pos, config,
                    cache=cache)


@pytest.mark.slow  # ~17 s; pipeline+dropout composition, tier-1 headroom
def test_pipeline_dropout_training():
    """Dropout composes with stage > 1: per-layer keys ride the staged
    tree and each stage folds in its current microbatch index, so every
    (layer, microbatch) pair draws an independent mask."""
    config, params, mesh, tokens = _setup(2)
    dcfg = config.replace(
        resid_pdrop=0.2, attn_pdrop=0.1, embd_pdrop=0.1, pp_microbatches=2
    )
    B, T = tokens.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    # Rows 0/1 and 2/3 identical: they land in DIFFERENT microbatches, so
    # under dropout their outputs must diverge (per-microbatch folding);
    # without dropout they must match exactly.
    tokens = jnp.concatenate([tokens[:2], tokens[:2]], axis=0)
    sharded = shard_params(params, mesh, dcfg)

    @jax.jit
    def run(p, t, q, rng):
        with use_mesh(mesh):
            return forward(p, t, q, dcfg, dropout_rng=rng)[0]

    @jax.jit
    def run_det(p, t, q):
        with use_mesh(mesh):
            return forward(p, t, q, dcfg)[0]

    det = np.asarray(run_det(sharded, tokens, pos))
    np.testing.assert_array_equal(det[:2], det[2:])  # sanity: same rows

    a = np.asarray(run(sharded, tokens, pos, jax.random.PRNGKey(1)))
    a2 = np.asarray(run(sharded, tokens, pos, jax.random.PRNGKey(1)))
    b = np.asarray(run(sharded, tokens, pos, jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(a, a2)        # same key -> same masks
    assert np.abs(a - det).max() > 0            # dropout actually applied
    assert np.abs(a - b).max() > 0              # key-sensitive
    assert np.abs(a[:2] - a[2:]).max() > 0      # per-microbatch masks

    # Pipeline training with dropout learns.
    opt = make_optimizer(learning_rate=1e-2, warmup_steps=0)
    state = init_train_state(sharded, opt)

    losses = []
    for i in range(20):
        state, loss = train_step(
            state, tokens, dcfg, opt, mesh=mesh,
            dropout_rng=jax.random.fold_in(jax.random.PRNGKey(7), i),
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9, losses[::5]
