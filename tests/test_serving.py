"""Continuous batching: requests entering/leaving slots independently must
each reproduce exactly what a standalone greedy generate produces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.serving import ContinuousBatcher

CFG = dict(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    multiple_of=32, max_seq_len=128, dtype="float32", param_dtype="float32",
)


def _reference(params, config, prompt, max_new, stop=()):
    """Standalone greedy generate for one prompt, trimmed like the batcher:
    tokens up to and including the stop token / max_new."""
    P = len(prompt)
    Pp = 1 << max(P - 1, 1).bit_length()
    toks = np.zeros((1, Pp), np.int32)
    mask = np.zeros((1, Pp), bool)
    toks[0, Pp - P:] = prompt
    mask[0, Pp - P:] = True
    gc = GenerationConfig(
        max_new_tokens=max_new, temperature=0.0, stop_tokens=tuple(stop),
        pad_id=0,
    )
    out = np.asarray(
        generate(params, jnp.asarray(toks), jnp.asarray(mask),
                 jax.random.PRNGKey(0), config=config, gen_config=gc)
    )[0, Pp:]
    emitted = []
    for t in out.tolist():
        emitted.append(t)
        if t in stop or len(emitted) >= max_new:
            break
    return emitted


@pytest.fixture(scope="module")
def model():
    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    return params, config


def test_single_request_matches_generate(model):
    params, config = model
    prompt = [5, 17, 99, 3, 42]
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rid = cb.submit(prompt, max_new_tokens=16)
    results = cb.run_to_completion()
    assert results[rid] == _reference(params, config, prompt, 16)


def test_staggered_requests_match_generate(model):
    """Requests submitted mid-flight (while other slots are decoding) must
    be unaffected by their neighbors."""
    params, config = model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 12)).tolist()
               for _ in range(6)]
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = {}
    results = {}
    # two initial requests; submit the rest as steps proceed
    rids[cb.submit(prompts[0], max_new_tokens=10)] = 0
    rids[cb.submit(prompts[1], max_new_tokens=7)] = 1
    submitted = 2
    guard = 0
    while cb.pending():
        guard += 1
        assert guard < 500
        for rid, tok, done in cb.step():
            results.setdefault(rid, []).append(tok)
        if submitted < len(prompts):
            rids[cb.submit(prompts[submitted],
                           max_new_tokens=5 + submitted)] = submitted
            submitted += 1
    assert len(results) == len(prompts)
    for rid, pi in rids.items():
        want = _reference(params, config, prompts[pi],
                          5 + pi if pi >= 2 else (10 if pi == 0 else 7))
        assert results[rid] == want, f"prompt {pi}"


def test_stop_tokens_free_slot(model):
    params, config = model
    prompt = [5, 17, 99, 3, 42]
    free_run = _reference(params, config, prompt, 16)
    # First token value that does not also occur earlier in the run
    # becomes the stop (so truncation-at-first-occurrence is unambiguous).
    j = next(
        i for i in range(1, len(free_run)) if free_run[i] not in free_run[:i]
    )
    stop = free_run[j]
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                           stop_tokens=(stop,))
    rid = cb.submit(prompt, max_new_tokens=16)
    results = cb.run_to_completion()
    assert results[rid] == free_run[:j + 1]
    assert not cb.pending()


def test_capacity_validation(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=32)
    with pytest.raises(ValueError, match="capacity"):
        cb.submit(list(range(1, 30)), max_new_tokens=16)


def test_queue_overflow_waits(model):
    """More requests than slots: the queue drains as slots free."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=64)
    r1 = cb.submit([4, 5, 6], max_new_tokens=4)
    r2 = cb.submit([7, 8, 9], max_new_tokens=4)
    results = cb.run_to_completion()
    assert set(results) == {r1, r2}
    assert results[r1] == _reference(params, config, [4, 5, 6], 4)
    assert results[r2] == _reference(params, config, [7, 8, 9], 4)


def test_capacity_check_uses_block_padded_length(model):
    """A 33-token prompt pads to the next block multiple (48 at block 16);
    with max_len=56 and max_new=16 the padded start (48) + 16 > 56 must be
    rejected up front — accepting it would silently drop decode KV writes
    past the reservation."""
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=56,
                           block_size=16)
    assert cb.block_size == 16
    with pytest.raises(ValueError, match="padded"):
        cb.submit(list(range(1, 34)), max_new_tokens=16)
    # 33 -> 48, 48 + 8 = 56 fits exactly
    rid = cb.submit(list(range(1, 34)), max_new_tokens=8)
    results = cb.run_to_completion()
    assert results[rid] == _reference(params, config, list(range(1, 34)), 8)


def test_no_pow2_waste(model):
    """Block padding reserves ceil((padded+max_new)/block) blocks — a
    65-token prompt at block 16 reserves 96 slots of KV (not the 128 a
    pow2 bucket would), so two such requests fit a 12-block pool."""
    params, config = model
    prompt = list(np.random.RandomState(1).randint(1, 128, size=65))
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=128,
                           block_size=16, n_blocks=12)
    r1 = cb.submit(prompt, max_new_tokens=8)
    r2 = cb.submit(prompt[:10], max_new_tokens=8)
    cb._admit()  # submit only queues; admission is a step-boundary batch
    # 65 -> 80 padded, +8 -> 88 -> 6 blocks; 10 -> 16, +8 -> 24 -> 2 blocks
    assert cb.slots[0] is not None and cb.slots[1] is not None
    results = cb.run_to_completion()
    assert results[r1] == _reference(params, config, prompt, 8)
    assert results[r2] == _reference(params, config, prompt[:10], 8)


def test_overcommit_pool_queues_until_blocks_free(model):
    """The pool may be smaller than n_slots x max_len (overcommit):
    requests whose reservation doesn't fit wait in the queue and run once
    completions free blocks — with contiguous per-slot regions this
    workload could not be configured at all."""
    params, config = model
    # 2 slots x max_len 96 would need 192 contiguous slots; pool holds 96.
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=96,
                           block_size=16, n_blocks=6)
    prompts = [[4, 5, 6], [7, 8, 9], [10, 11, 12]]
    rids = [cb.submit(p, max_new_tokens=30) for p in prompts]
    cb._admit()  # submit only queues; admission is a step-boundary batch
    # each request reserves ceil((16+30)/16) = 3 blocks; only two fit at
    # once, the third queues.
    assert sum(s is not None for s in cb.slots.values()) == 2
    assert len(cb.queue) == 1
    results = cb.run_to_completion()
    for rid, p in zip(rids, prompts):
        assert results[rid] == _reference(params, config, p, 30)
    assert sorted(cb.free_blocks) == list(range(6))


def test_oversized_reservation_rejected(model):
    params, config = model
    cb = ContinuousBatcher(params, config, n_slots=1, max_len=96,
                           block_size=16, n_blocks=3)
    with pytest.raises(ValueError, match="blocks"):
        cb.submit([1, 2, 3], max_new_tokens=70)


def _reference_sampled(params, config, prompt, max_new, seed, temperature,
                       top_p=None, top_k=None):
    """Standalone SAMPLED generate for one prompt (B=1), trimmed like the
    batcher."""
    P = len(prompt)
    Pp = 1 << max(P - 1, 1).bit_length()
    toks = np.zeros((1, Pp), np.int32)
    mask = np.zeros((1, Pp), bool)
    toks[0, Pp - P:] = prompt
    mask[0, Pp - P:] = True
    gc = GenerationConfig(
        max_new_tokens=max_new, temperature=temperature, top_p=top_p,
        top_k=top_k, stop_tokens=(), pad_id=0,
    )
    out = np.asarray(
        generate(params, jnp.asarray(toks), jnp.asarray(mask),
                 jax.random.PRNGKey(seed), config=config, gen_config=gc)
    )[0, Pp:]
    return out[:max_new].tolist()


def test_per_request_sampling_matches_standalone(model):
    """Each slot's (seed, temperature, top_p, top_k) must reproduce the
    standalone seeded engine.generate of that request exactly, even while
    sharing decode steps with slots running different policies."""
    params, config = model
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 128, size=rng.randint(3, 9)).tolist()
               for _ in range(4)]
    policies = [
        dict(temperature=0.0),
        dict(temperature=0.9, seed=11),
        dict(temperature=0.7, top_p=0.8, seed=12),
        dict(temperature=1.1, top_k=20, seed=13),
    ]
    cb = ContinuousBatcher(params, config, n_slots=2, max_len=64)
    rids = [cb.submit(p, max_new_tokens=8, **pol)
            for p, pol in zip(prompts, policies)]
    results = cb.run_to_completion()
    for rid, p, pol in zip(rids, prompts, policies):
        t = pol["temperature"]
        if t == 0.0:
            want = _reference(params, config, p, 8)
        else:
            want = _reference_sampled(
                params, config, p, 8, pol["seed"], t,
                pol.get("top_p"), pol.get("top_k"),
            )
        assert results[rid] == want, pol


def test_sampled_pool_runs_and_varies(model):
    """temperature > 0: the pool samples; different seeds give different
    outputs (overwhelmingly), same seed reproduces."""
    params, config = model
    prompt = [5, 17, 99, 3, 42]

    def run(seed):
        cb = ContinuousBatcher(params, config, n_slots=2, max_len=64,
                               temperature=0.9, seed=seed)
        rid = cb.submit(prompt, max_new_tokens=12)
        return cb.run_to_completion()[rid]

    a, b, c = run(0), run(0), run(1)
    assert a == b            # deterministic per seed
    assert a != c            # varies across seeds
    assert all(0 <= t < 128 for t in a)


def test_int8_kv_paged_batcher(model):
    """The paged pool's quantized branches (scale gather/scatter through
    block tables) must produce the same tokens as the standalone int8-KV
    generate path."""
    params, config = model
    import dataclasses
    qconfig = dataclasses.replace(config, kv_cache_dtype="int8")
    prompt = [5, 17, 99, 3, 42]
    cb = ContinuousBatcher(params, qconfig, n_slots=2, max_len=64,
                           block_size=16)
    assert cb.pool.quantized
    rid = cb.submit(prompt, max_new_tokens=12)
    got = cb.run_to_completion()[rid]
    want = _reference(params, qconfig, prompt, 12)
    assert got == want
    # int8 quantization changes numerics vs fp32 but stays plausible
    assert all(0 <= t < 128 for t in got)


def test_chunked_admission_matches_single_shot(model):
    """Batcher prefill in chunks must yield identical completions."""
    params, config = model
    prompt = list(np.random.RandomState(3).randint(1, 128, size=23))
    want = _reference(params, config, prompt, 10)
    for chunk in (8, 16, None):
        cb = ContinuousBatcher(params, config, n_slots=1, max_len=64,
                               prefill_chunk=chunk)
        rid = cb.submit(prompt, max_new_tokens=10)
        assert cb.run_to_completion()[rid] == want, f"chunk={chunk}"


def test_logprobs_match_engine_score():
    """With logprobs=True the batcher's per-token logprob equals
    engine.score's teacher-forced log p(token | prefix) at the same
    position — for greedy AND sampled slots (the definition is the raw
    model distribution, temperature-independent)."""
    from jax_llama_tpu.engine import score

    config = get_config("tiny", **CFG)
    params = init_params(jax.random.PRNGKey(0), config)
    rng = np.random.RandomState(21)
    prompts = [list(rng.randint(1, 128, n)) for n in (6, 17)]

    cb = ContinuousBatcher(
        params, config, n_slots=2, max_len=64, block_size=16, logprobs=True,
    )
    r0 = cb.submit(prompts[0], max_new_tokens=8)                  # greedy
    r1 = cb.submit(prompts[1], max_new_tokens=8, temperature=0.7,
                   top_p=0.9, seed=5)                             # sampled
    got: dict = {}
    lps: dict = {}
    while cb.pending():
        for rid, tok, done, lp in cb.step():
            got.setdefault(rid, []).append(tok)
            lps.setdefault(rid, []).append(lp)

    for rid, prompt in ((r0, prompts[0]), (r1, prompts[1])):
        toks = got[rid]
        full = jnp.asarray([prompt + toks], jnp.int32)
        # score[t] = log p(full[t+1] | full[:t+1]); emitted token i sits
        # at full position len(prompt)+i, so its score index is
        # len(prompt)+i-1.
        sc = np.asarray(score(params, full, config=config))[0]
        want = [float(sc[len(prompt) + i - 1]) for i in range(len(toks))]
        np.testing.assert_allclose(lps[rid], want, atol=1e-4, rtol=1e-4)




def test_host_threefry_key_layout():
    """_admit builds each request's PRNG key on the host as
    [0, seed & 0xFFFFFFFF] instead of fetching jax.random.PRNGKey from
    the device (a ~100ms tunnel round-trip per admission on real
    hardware).  Pin the layout equivalence so a PRNG-impl or
    canonicalization change can't silently fork the batcher's sampled
    outputs from standalone seeded generates."""
    for seed in (0, 1, 7, 2**31 - 1, -1, -12345, (123 << 32) | 7):
        expect = np.asarray(jax.random.PRNGKey(seed))
        host = np.array([0, seed & 0xFFFFFFFF], np.uint32)
        assert (expect == host).all(), (seed, expect, host)


def test_block_size_tiered_default():
    """The default block size trades allocation granularity for kernel
    DMA efficiency as capacity grows (on-chip swept r4: 16k serving
    decode 8.9 -> 5.8 ms/step going 128 -> 512); explicit block_size
    still wins."""
    cfg = get_config(
        "tiny", dim=64, n_layers=2, n_heads=2, n_kv_heads=1,
        vocab_size=128, max_seq_len=16384,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    for max_len, expect in ((512, 32), (2048, 128), (8192, 512),
                            (16384, 512)):
        cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=max_len)
        assert cb.block_size == expect, (max_len, cb.block_size)
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=16384,
                           block_size=64)
    assert cb.block_size == 64
