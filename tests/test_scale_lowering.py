"""Full-scale (70B-class) shape/lowering checks — no weights materialized.

The reference can only express 70B through its MP table (README.md:44-53);
nothing in its repo validates the shapes.  Here the real llama3-70b config
is traced abstractly through train and decode paths on an 8-device mesh:
eval_shape catches dimension/sharding-rule bugs at scale in seconds, and
jit lowering exercises the scan-over-layers claim (80 layers trace as fast
as 4 — no Python-unrolled stack, reference model.py:579-592).
"""

import jax
import jax.numpy as jnp
import numpy as np

from jax_llama_tpu import get_config, make_mesh
from jax_llama_tpu.engine import GenerationConfig, generate
from jax_llama_tpu.models import forward
from jax_llama_tpu.models.llama import init_params
from jax_llama_tpu.parallel import param_partition_specs, use_mesh, validate_tp


def _abstract_params(config):
    return jax.eval_shape(lambda k: init_params(k, config), jax.random.PRNGKey(0))


def test_llama3_70b_param_count():
    config = get_config("llama3-70b")
    shapes = _abstract_params(config)
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    assert 69e9 < n < 72e9, n  # published: 70.6B


def test_llama3_70b_partition_specs_cover_tree():
    config = get_config("llama3-70b")
    shapes = _abstract_params(config)
    specs = param_partition_specs(config, fsdp=True, pp=True)
    # mirror-shaped: zipping must succeed and cover every leaf
    zipped = jax.tree.map(lambda a, b: (a, b), shapes, specs)
    assert len(jax.tree.leaves(zipped, is_leaf=lambda x: isinstance(x, tuple)))


def test_llama3_70b_tp8_divisibility():
    config = get_config("llama3-70b")
    mesh = make_mesh(tensor=8, devices=np.tile(jax.devices(), 1)[:8])
    validate_tp(config, mesh, fsdp=False)  # v5p-64-style TP8 must divide


def test_llama3_70b_forward_eval_shape():
    config = get_config("llama3-70b", max_seq_len=8192)
    shapes = _abstract_params(config)
    B, T = 4, 8192
    tokens = jax.ShapeDtypeStruct((B, T), jnp.int32)
    pos = jax.ShapeDtypeStruct((B, T), jnp.int32)
    out, _ = jax.eval_shape(
        lambda p, t, q: forward(p, t, q, config), shapes, tokens, pos
    )
    assert out.shape == (B, T, config.vocab_size)


def test_llama3_70b_decode_lowering_80_layers():
    """jit-lower (not compile) the full decode engine for the 80-layer
    model on a TP8 mesh — completes in seconds because the layer stack is
    a scan, and catches sharding/shape errors in the whole pipeline."""
    config = get_config("llama3-70b", max_seq_len=512)
    mesh = make_mesh(tensor=8, devices=jax.devices()[:8])
    shapes = _abstract_params(config)
    B, P = 2, 128
    tokens = jax.ShapeDtypeStruct((B, P), jnp.int32)
    mask = jax.ShapeDtypeStruct((B, P), jnp.bool_)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    gc = GenerationConfig(max_new_tokens=64, temperature=0.0)
    lowered = generate.lower(
        shapes, tokens, mask, key, config=config, gen_config=gc, mesh=mesh
    )
    assert "while" in lowered.as_text()  # the decode loop lowered


def test_llama3_70b_train_eval_shape_pp_fsdp():
    """Abstract train-shapes on a stage*fsdp*tensor mesh at 70B scale."""
    from jax_llama_tpu.train import lm_loss

    config = get_config("llama3-70b", max_seq_len=4096, remat=True)
    mesh = make_mesh(stage=2, fsdp=2, tensor=2, devices=jax.devices()[:8])
    shapes = _abstract_params(config)
    tokens = jax.ShapeDtypeStruct((8, 4096), jnp.int32)
    with use_mesh(mesh):
        loss = jax.eval_shape(lambda p, t: lm_loss(p, t, config), shapes, tokens)
    assert loss.shape == ()
