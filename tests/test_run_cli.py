"""Serving CLI end-to-end smoke test (parity: reference jax_example.main,
/root/reference/jax_example.py:33-43 — load weights, complete prompts) —
run against a tiny Orbax checkpoint with the byte tokenizer."""

import sys

import jax
import pytest

from jax_llama_tpu import get_config, init_params
from jax_llama_tpu.convert.checkpoint import save_checkpoint
import jax_llama_tpu.run as run_cli


def test_run_cli_end_to_end(tmp_path, capsys, monkeypatch):
    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--prompt", "hello world",
         "--max-gen-len", "8", "--temperature", "0.0"],
    )
    run_cli.main()
    out = capsys.readouterr().out
    assert "restored" in out
    assert "'hello world'" in out
    assert "tok/s" in out or "summary" in out or "[" in out


def test_run_cli_requires_tokenizer(tmp_path, monkeypatch):
    monkeypatch.setattr(
        sys, "argv", ["run", "--ckpt-dir", str(tmp_path)],
    )
    with pytest.raises(SystemExit):
        run_cli.main()


def test_run_cli_serve_mode(tmp_path, capsys, monkeypatch):
    """--serve streams completions for stdin prompts via the batcher."""
    import io

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer", "--serve",
         "--slots", "2", "--tensor", "2", "--max-gen-len", "6",
         "--temperature", "0.0"],
    )
    monkeypatch.setattr(sys, "stdin", io.StringIO("hello\nworld\n"))
    run_cli.main()
    out = capsys.readouterr().out
    assert "'hello'" in out and "'world'" in out
    assert "served 2 request(s)" in out


def test_run_cli_http_mode(tmp_path, capsys, monkeypatch):
    """--http starts LLMServer over the batcher; requests served live
    (driven in-process via the test hook instead of the blocking loop)."""
    import json
    import urllib.request

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    hits = {}

    def hook(srv):
        req = urllib.request.Request(
            srv.address + "/generate",
            data=json.dumps(
                {"text": "hi", "max_new_tokens": 4, "temperature": 0.0}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            hits["gen"] = json.loads(r.read())
        with urllib.request.urlopen(srv.address + "/healthz", timeout=60) as r:
            hits["health"] = json.loads(r.read())

    orig = run_cli._serve_http
    monkeypatch.setattr(
        run_cli, "_serve_http",
        lambda *a, **kw: orig(*a, **kw, _test_hook=hook),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--http", "0", "--max-gen-len", "8",
         "--temperature", "0.0"],
    )
    run_cli.main()
    out = capsys.readouterr().out
    # The operational log line goes through obs.StructuredLogger now:
    # "serving address=http://... endpoints=..." in text mode.
    assert "serving" in out and "http://" in out
    assert len(hits["gen"]["tokens"]) == 4 and "text" in hits["gen"]
    assert hits["health"]["ok"] is True


def test_run_cli_http_log_json(tmp_path, capsys, monkeypatch):
    """--log-json routes every operational line through one JSON
    formatter: each log line parses as a JSON object with an "event"
    field (checkpoint_restored, serving, ...) — no bare prints left on
    the serving path."""
    import json
    import urllib.request

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    def hook(srv):
        with urllib.request.urlopen(srv.address + "/healthz", timeout=60):
            pass

    orig = run_cli._serve_http
    monkeypatch.setattr(
        run_cli, "_serve_http",
        lambda *a, **kw: orig(*a, **kw, _test_hook=hook),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--tensor", "2", "--http", "0", "--log-json"],
    )
    run_cli.main()
    lines = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.strip()
    ]
    assert lines, "expected structured log output"
    events = []
    for ln in lines:
        rec = json.loads(ln)  # every line is one JSON object
        assert "event" in rec and "ts" in rec
        events.append(rec["event"])
    assert "checkpoint_restored" in events
    assert "serving" in events


@pytest.mark.slow
def test_run_cli_serve_mesh_and_replicas(tmp_path, capsys, monkeypatch):
    """--serve-mesh dp,tp + --replicas N: requests served through the
    ReplicaRouter on mesh-placed replicas, end-to-end from the CLI.
    Slow tier (compiles a mesh'd checkpoint-restored model; the flag
    surface is pinned tier-1 below, the routed/mesh behavior by
    test_router.py + test_serve_mesh.py, and make mesh-serve runs
    this cell)."""
    import json
    import urllib.request

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        multiple_of=32, max_seq_len=64,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    hits = {}

    def hook(router, servers):
        req = urllib.request.Request(
            router.address + "/generate",
            data=json.dumps(
                {"text": "hi", "max_new_tokens": 4, "temperature": 0.0}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            hits["gen"] = json.loads(r.read())
            hits["replica"] = r.headers.get("X-Replica-Id")
        with urllib.request.urlopen(
            router.address + "/healthz", timeout=60
        ) as r:
            hits["health"] = json.loads(r.read())
        hits["meshes"] = [
            dict(s.batcher.mesh.shape) if s.batcher.mesh is not None
            else None
            for s in servers
        ]
        hits["placed"] = [s.batcher._mesh_placed for s in servers]

    orig = run_cli._serve_router
    monkeypatch.setattr(
        run_cli, "_serve_router",
        lambda *a, **kw: orig(
            *a, **{**kw, "_test_hook": hook},
        ),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--http", "0", "--serve-mesh", "1,2", "--replicas", "2",
         "--route", "affinity", "--slots", "2"],
    )
    run_cli.main()
    assert len(hits["gen"]["tokens"]) == 4
    assert hits["replica"] in ("0", "1")
    h = hits["health"]
    assert h["ok"] and h["policy"] == "affinity"
    assert len(h["replicas"]) == 2
    # 8 forced host devices / (1*2 per replica) -> each replica got its
    # own device slice on its own 1x2 serving mesh, placement active.
    assert all(m and m.get("tensor") == 2 for m in hits["meshes"])
    assert hits["placed"] == [True, True]


def test_run_cli_serve_mesh_flag_validation(tmp_path, monkeypatch):
    """Bad scale-out flag combinations refuse loudly at startup."""
    # --replicas needs --http.
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
         "--replicas", "2"],
    )
    with pytest.raises(SystemExit, match="replicas"):
        run_cli.main()
    # --serve-mesh needs a serving mode.
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
         "--serve-mesh", "1,2"],
    )
    with pytest.raises(SystemExit, match="serve-mesh"):
        run_cli.main()
    # Malformed geometry.
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
         "--http", "0", "--serve-mesh", "1,2,3"],
    )
    with pytest.raises(SystemExit, match="serve-mesh"):
        run_cli.main()
    # More devices than the host has.
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
         "--http", "0", "--serve-mesh", "4,4"],
    )
    with pytest.raises(SystemExit, match="devices"):
        run_cli.main()
    # --replica-roles: wrong count, bad role, missing a role class,
    # and the cache-aware policy requirement — all pre-weight-load.
    base = ["run", "--ckpt-dir", str(tmp_path), "--byte-tokenizer",
            "--http", "0", "--replicas", "2"]
    for extra, msg in (
        (["--replica-roles", "prefill"], "one role per replica"),
        (["--replica-roles", "prefill,cook"], "unknown role"),
        (["--replica-roles", "prefill,prefill",
          "--route", "cache-aware"], "EACH role"),
        (["--replica-roles", "prefill,decode"], "cache-aware"),
    ):
        monkeypatch.setattr(sys, "argv", base + extra)
        with pytest.raises(SystemExit, match=msg):
            run_cli.main()


@pytest.mark.slow
def test_run_cli_cache_aware_disaggregation(
    tmp_path, capsys, monkeypatch,
):
    """--route cache-aware + --replica-roles prefill,decode from the
    CLI: a cold session prefills on replica 0, its chain streams to
    the decode replica, and the revisit lands there warm (slow tier;
    make fleet runs it — the routing/scheduler behavior itself is
    pinned tier-1 by test_cache_routing.py)."""
    import json
    import urllib.request

    config = get_config(
        "tiny", vocab_size=512, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, multiple_of=32, max_seq_len=96,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), params, config)

    hits = {}

    def post(url, payload):
        req = urllib.request.Request(
            url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            return json.loads(r.read()), r.headers.get("X-Replica-Id")

    session = "the quick brown fox jumps over the lazy d"

    def hook(router, servers):
        _, rep0 = post(
            router.address,
            {"text": session, "max_new_tokens": 4,
             "temperature": 0.0},
        )
        hits["cold_replica"] = rep0
        hits["handoff_done"] = router.wait_handoffs(20.0)
        _, rep1 = post(
            router.address,
            {"text": session + " and a second turn",
             "max_new_tokens": 4, "temperature": 0.0},
        )
        hits["revisit_replica"] = rep1
        hits["health"] = router.health()

    orig = run_cli._serve_router
    monkeypatch.setattr(
        run_cli, "_serve_router",
        lambda *a, **kw: orig(*a, **{**kw, "_test_hook": hook}),
    )
    monkeypatch.setattr(
        sys, "argv",
        ["run", "--ckpt-dir", str(ckpt), "--byte-tokenizer",
         "--http", "0", "--replicas", "2", "--route", "cache-aware",
         "--replica-roles", "prefill,decode", "--slots", "2",
         "--tensor", "1"],
    )
    run_cli.main()
    assert hits["cold_replica"] == "0"  # prefill role
    assert hits["handoff_done"]
    assert hits["revisit_replica"] == "1"  # decodes warm
    h = hits["health"]
    assert h["policy"] == "cache-aware"
    assert h["roles"] == ["prefill", "decode"]
    assert h["handoff"]["completed_total"] >= 1
